"""swarmdurable (ISSUE 14): crash-safe hive — journaled queue state,
deterministic recovery replay, worker-side hive-outage ride-through.

Four layers:

- **Journal hygiene units** (no hive): append/commit/replay round
  trips, segment rotation, torn-final-record repair (``.bad`` parked +
  counted), corrupt-mid-log recovery (longest consistent prefix), and
  compaction equivalence — replay(snapshot + tail) == replay(full log).
- **Recovery protocol units** (fake clock, no workers): a recovered
  hive rebuilds queue + lease books + checkpoints + flight records,
  bumps the epoch, redelivers pre-crash leases WITH their journaled
  resume state, dedupes pre-crash settles, salvages pre-epoch uploads
  exactly once, and rejects a stale worker's heartbeat via the epoch
  handshake. Without a journal the wire shape is byte-compatible with
  today (the parity gate).
- **Ride-through fleet chaos** (real Worker + ChaoticExecutor): the
  hive is SIGKILL'd under a live worker — the session flips to OUTAGE,
  in-flight work completes, results spool, and the restarted hive
  (same port, recovered from its journal) receives everything exactly
  once via the LIVE dead-letter replay.
- **THE acceptance gate** (real lanes, slow tier): 3 lane workers, the
  hive SIGKILL'd mid-lane and restarted from its journal — zero job
  loss, exactly-once settlement across epochs, a redelivered job
  provably resumes at step >= 1 from the JOURNALED checkpoint, and one
  stitched flight record spans both hive epochs.

Everything is hermetic (loopback only) and scripted/seeded.
"""

from __future__ import annotations

import asyncio
import json
import shutil
import time

import pytest

from chiaswarm_tpu.node.chaos import ChaoticExecutor
from chiaswarm_tpu.node.executor import error_result
from chiaswarm_tpu.node.hivelog import HIVE_EPOCH_KEY, HiveJournal
from chiaswarm_tpu.node.minihive import (
    MiniHive,
    kill_hive,
    restart_hive,
)
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.resilience import HiveSession
from chiaswarm_tpu.node.settings import Settings
from chiaswarm_tpu.node.worker import Worker


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


@pytest.fixture(autouse=True)
def _restore_matmul_precision():
    import jax

    before = jax.config.jax_default_matmul_precision
    yield
    jax.config.update("jax_default_matmul_precision", before)


class StubSlot:
    def __init__(self, depth: int = 2, data_width: int = 1,
                 name: str = "stub"):
        self.depth = depth
        self.data_width = data_width
        self.name = name

    def descriptor(self):
        return self.name


def fleet_settings(uri: str, name: str, **over) -> Settings:
    base = dict(
        hive_uri=uri, hive_token="t", worker_name=name,
        job_deadline_s=5.0,
        transient_retries=1,
        retry_backoff_s=0.01, retry_backoff_cap_s=0.05,
        breaker_threshold=5, breaker_cooldown_s=3600.0,
        poll_busy_s=0.02, poll_idle_s=0.04,
        poll_backoff_base_s=0.02, poll_backoff_cap_s=0.1,
        upload_retries=3, upload_retry_delay_s=0.02,
        drain_timeout_s=5.0, result_drain_timeout_s=5.0,
        install_signal_handlers=False,
        heartbeat_s=0.05,
    )
    base.update(over)
    return Settings(**base)


def _job(job_id: str, chaos=None, model: str = "shared/tiny", **over):
    job = {"id": job_id, "model_name": model, "prompt": f"p {job_id}",
           "num_inference_steps": 2, "height": 64, "width": 64,
           "content_type": "application/json"}
    if chaos is not None:
        job["chaos"] = chaos
    job.update(over)
    return job


def _ok_result(job_id: str, worker: str = "", epoch=None) -> dict:
    result = {"id": job_id, "artifacts": {}, "nsfw": False,
              "pipeline_config": {"mode": "test"}}
    if worker:
        result["worker_name"] = worker
    if epoch is not None:
        result[HIVE_EPOCH_KEY] = epoch
    return result


def _journal(tmp_path, name="hive", **over) -> HiveJournal:
    over.setdefault("fsync", False)  # logic under test, not the disk
    return HiveJournal(tmp_path / name, **over)


def _hive(journal=None, clock=None, **over) -> MiniHive:
    kwargs = dict(lease_s=5.0, max_attempts=3, max_jobs_per_poll=0)
    kwargs.update(over)
    if clock is not None:
        kwargs["clock"] = clock
    return MiniHive(journal=journal, **kwargs)


# ---------------------------------------------------------------------------
# journal hygiene units
# ---------------------------------------------------------------------------


def test_journal_append_commit_replay_roundtrip(tmp_path):
    journal = _journal(tmp_path)
    assert journal.stored_epoch() == 0
    for i in range(5):
        journal.append("submit", id=f"j{i}", t=float(i))
    assert journal.records_written == 0  # nothing durable pre-commit
    assert journal.commit() == 5
    journal.append("grant", id="j0", t=9.0, attempt=1, worker="w")
    journal.commit()
    journal.close()

    snapshot, records = _journal(tmp_path).replay()
    assert snapshot is None
    assert [r["ev"] for r in records] == ["submit"] * 5 + ["grant"]
    assert [r["seq"] for r in records] == list(range(1, 7))
    assert records[-1]["worker"] == "w"


def test_journal_segment_rotation_spans_replay(tmp_path):
    journal = _journal(tmp_path, segment_bytes=1)  # clamped to 4096
    journal.segment_bytes = 256  # force rotation every few records
    for i in range(40):
        journal.append("submit", id=f"j{i}", t=float(i),
                       job={"id": f"j{i}", "prompt": "x" * 64})
        journal.commit()
    journal.close()
    assert len(journal._segments()) > 1

    _, records = _journal(tmp_path).replay()
    assert [r["seq"] for r in records] == list(range(1, 41))


def test_journal_torn_final_record_parked(tmp_path):
    journal = _journal(tmp_path)
    for i in range(4):
        journal.append("submit", id=f"j{i}", t=float(i))
    journal.commit()
    journal.close()
    # a SIGKILL mid-write tears the final record: no newline, half JSON
    segment = journal._segments()[-1]
    with open(segment, "ab") as fh:
        fh.write(b'{"seq": 5, "ev": "gra')

    reopened = _journal(tmp_path)
    _, records = reopened.replay()
    assert [r["seq"] for r in records] == [1, 2, 3, 4]
    assert reopened.tails_parked == 1
    bad = list(tmp_path.glob("hive/*.bad"))
    assert len(bad) == 1 and b"gra" in bad[0].read_bytes()
    # the repaired journal appends cleanly after the last good record
    reopened.append("submit", id="j9", t=9.0)
    reopened.commit()
    reopened.close()
    fresh = _journal(tmp_path)
    _, records = fresh.replay()
    assert [r["seq"] for r in records] == [1, 2, 3, 4, 5]
    assert fresh.tails_parked == 0  # already repaired last time


def test_journal_corrupt_mid_record_stops_at_prefix(tmp_path):
    journal = _journal(tmp_path)
    for i in range(6):
        journal.append("submit", id=f"j{i}", t=float(i))
    journal.commit()
    journal.close()
    segment = journal._segments()[-1]
    lines = segment.read_bytes().splitlines(keepends=True)
    lines[3] = b'{"seq": 4, "ev": CORRUPT}\n'
    segment.write_bytes(b"".join(lines))

    reopened = _journal(tmp_path)
    _, records = reopened.replay()
    # longest consistent prefix: records 1-3; 4+ parked as .bad
    assert [r["seq"] for r in records] == [1, 2, 3]
    assert reopened.tails_parked == 1
    assert reopened.last_seq == 3
    bad = list(tmp_path.glob("hive/*.bad"))
    assert len(bad) == 1 and b"CORRUPT" in bad[0].read_bytes()


def test_journal_commit_failure_keeps_batch_and_rolls_back(tmp_path):
    """A transient write failure must not drop the batch: the seqs are
    already assigned, so losing them would leave a permanent sequence
    gap every future replay stops at. The failed commit raises (the
    hive never acks), keeps the buffer, rolls the segment back to its
    known-good prefix — and the retry lands gapless."""
    journal = _journal(tmp_path)
    journal.append("submit", id="a", t=0.0)
    journal.commit()
    journal.append("submit", id="b", t=1.0)
    real_fh = journal._fh

    class FailingFH:
        def write(self, data):
            raise OSError(28, "No space left on device")

        def __getattr__(self, name):
            return getattr(real_fh, name)

    journal._fh = FailingFH()
    with pytest.raises(OSError):
        journal.commit()
    journal._fh = real_fh
    assert journal.commit() == 1  # the batch survived; retry succeeds
    journal.close()
    _, records = _journal(tmp_path).replay()
    assert [r["seq"] for r in records] == [1, 2]
    assert [r["id"] for r in records] == ["a", "b"]


def test_constructor_attach_repairs_torn_tail(tmp_path):
    """Attaching a journal via the MiniHive constructor (not recover)
    must run the repairing replay FIRST: appending a new epoch after a
    crash-torn tail would otherwise put every post-attach record behind
    bytes a future recovery parks wholesale."""
    journal = _journal(tmp_path)
    hive = _hive(journal=journal, clock=lambda: 0.0)
    hive.submit(_job("old-0"))
    journal.close()
    segment = journal._segments()[-1]
    with open(segment, "ab") as fh:
        fh.write(b'{"seq": 99, "ev": "gra')  # the SIGKILL tear

    attached = _hive(journal=_journal(tmp_path), clock=lambda: 0.0)
    assert attached.journal.tails_parked == 1  # repaired at attach
    assert attached.hive_epoch == 2
    attached.submit(_job("new-0"))
    attached.journal.close()
    # recovery replays BOTH lives' records — nothing post-attach was
    # parked behind the (already-repaired) tear
    recovered = MiniHive.recover(_journal(tmp_path),
                                 clock=lambda: 0.0)
    pending = {str(j["id"]) for j in recovered.pending_jobs}
    assert "new-0" in pending
    assert recovered.hive_epoch == 3


def test_journal_sequence_gap_detected(tmp_path):
    journal = _journal(tmp_path)
    for i in range(4):
        journal.append("submit", id=f"j{i}", t=float(i))
    journal.commit()
    journal.close()
    segment = journal._segments()[-1]
    lines = segment.read_bytes().splitlines(keepends=True)
    del lines[2]  # silently lose seq 3 — replay must NOT bridge the gap
    segment.write_bytes(b"".join(lines))

    reopened = _journal(tmp_path)
    _, records = reopened.replay()
    assert [r["seq"] for r in records] == [1, 2]
    assert reopened.tails_parked == 1


def _drive_ops(hive, clock) -> list[str]:
    """A deterministic op mix covering every journaled transition:
    settles, redispatch, duplicate, lease expiry, abandonment, and a
    straggler salvage."""
    issued = [f"op-{i}" for i in range(8)]
    for job_id in issued:
        hive.submit(_job(job_id))
    clock[0] += 0.1
    handed = hive._take_jobs("wA")
    assert len(handed) == 8
    # settle 3 normally (one twice: a duplicate ack)
    for job_id in ("op-0", "op-1", "op-2"):
        assert hive._record_result(_ok_result(job_id, "wA"),
                                   "wA")["status"] == "ok"
    assert hive._record_result(_ok_result("op-0", "wB"),
                               "wB")["status"] == "duplicate"
    # redispatch one by error kind
    assert hive._record_result(
        error_result(_job("op-3"), "nope", kind="model_unavailable"),
        "wA")["status"] == "requeued"
    # march op-4..7 through lease expiry to abandonment (max_attempts)
    for _ in range(hive.max_attempts + 1):
        clock[0] += hive.lease_s + 0.1
        hive.sweep()
        hive._take_jobs("wB")
        clock[0] += 0.05
    clock[0] += hive.lease_s + 0.1
    hive.sweep()
    assert hive.abandoned, "abandonment never exercised"
    # a straggler upload salvages one abandoned job
    salvage_id = hive.abandoned[0]
    assert hive._record_result(_ok_result(salvage_id, "wB"),
                               "wB")["status"] == "ok"
    return issued


def test_compaction_equivalence_snapshot_plus_tail(tmp_path):
    """replay(snapshot + tail) must rebuild EXACTLY the state
    replay(full log) does — dump_state to dump_state, counters and
    flight records included."""
    clock = [0.0]
    journal = _journal(tmp_path, "hive", compact_every=0)
    hive = _hive(journal=journal, clock=lambda: clock[0])
    for i in range(4):
        hive.submit(_job(f"pre-{i}"))
    clock[0] += 0.1
    hive._take_jobs("wA")
    hive._record_result(_ok_result("pre-0", "wA"), "wA")
    # snapshot mid-history, KEEPING the covered segments so both replay
    # paths stay available over one identical event stream
    journal.write_snapshot(hive.dump_state(), epoch=hive.hive_epoch,
                           t=clock[0], prune=False)
    # tail ops after the snapshot
    _drive_ops(hive, clock)
    journal.close()

    # twin B: the same journal without its snapshot = the full log
    shutil.copytree(tmp_path / "hive", tmp_path / "hive-full")
    for snap in (tmp_path / "hive-full").glob("snapshot-*.json"):
        snap.unlink()

    recovered_snap = MiniHive.recover(
        _journal(tmp_path, "hive"), lease_s=5.0, max_attempts=3,
        clock=lambda: clock[0])
    recovered_full = MiniHive.recover(
        _journal(tmp_path, "hive-full"), lease_s=5.0, max_attempts=3,
        clock=lambda: clock[0])
    state_snap = recovered_snap.dump_state()
    state_full = recovered_full.dump_state()
    assert state_snap == state_full
    assert recovered_snap.hive_epoch == recovered_full.hive_epoch == 2
    # and both reconcile: the durable counters agree with the lists
    for hive2 in (recovered_snap, recovered_full):
        assert hive2._completed.value() == len(hive2.completed)
        assert hive2._abandoned.value() == \
            len(hive2.abandoned) + hive2._salvaged.value()


def test_compaction_prunes_segments_and_auto_triggers(tmp_path):
    clock = [0.0]
    journal = _journal(tmp_path, compact_every=10)
    hive = _hive(journal=journal, clock=lambda: clock[0])
    for i in range(12):  # > compact_every records via submits + grants
        hive.submit(_job(f"c-{i}"))
    clock[0] += 0.1
    hive._take_jobs("wA")
    assert journal.snapshots_written >= 1
    assert journal.segments_pruned >= 1
    # recovery over the pruned journal still sees everything
    journal.close()
    recovered = MiniHive.recover(_journal(tmp_path), lease_s=5.0,
                                 max_attempts=3,
                                 clock=lambda: clock[0])
    assert len(recovered.leases) + len(recovered.pending_jobs) == 12


# ---------------------------------------------------------------------------
# recovery protocol units (fake clock)
# ---------------------------------------------------------------------------


def test_recover_rebuilds_queue_leases_checkpoints_and_redelivers(
        tmp_path):
    clock = [0.0]
    journal = _journal(tmp_path)
    hive = _hive(journal=journal, clock=lambda: clock[0],
                 max_jobs_per_poll=2)
    assert hive.hive_epoch == 1
    for i in range(4):
        hive.submit(_job(f"r-{i}"))
    clock[0] += 0.1
    handed = hive._take_jobs("w1")
    assert [p[HIVE_EPOCH_KEY] for p in handed] == [1, 1]
    trace_ids = {p["id"]: p["trace_ctx"]["trace_id"] for p in handed}
    # heartbeat checkpoint custody rides the journal (direct append —
    # the HTTP handler unit is covered by the handshake test below)
    hive.checkpoints["r-0"] = {"kind": "lane", "step": 7}
    hive._journal("checkpoint", id="r-0", t=clock[0], worker="w1",
                  checkpoint={"kind": "lane", "step": 7})
    hive._journal_commit()
    assert hive._record_result(_ok_result("r-1", "w1", epoch=1),
                               "w1")["status"] == "ok"
    journal.close()
    # the crash: in-memory hive is garbage; recover from the journal
    recovered = MiniHive.recover(_journal(tmp_path), lease_s=5.0,
                                 max_attempts=3, max_jobs_per_poll=0,
                                 clock=lambda: clock[0])
    assert recovered.hive_epoch == 2
    # settled job deduped across the restart
    assert recovered.completed["r-1"]["recovered"] is True
    assert recovered._record_result(
        _ok_result("r-1", "w1", epoch=1), "w1") == {"status": "duplicate"}
    # pre-crash leases are void: first sweep redelivers r-0 WITH its
    # journaled checkpoint, and the queue copy of r-2/r-3 survives
    clock[0] += 0.01
    handed2 = recovered._take_jobs("w2")
    by_id = {p["id"]: p for p in handed2}
    assert set(by_id) == {"r-0", "r-2", "r-3"}
    assert by_id["r-0"]["attempt"] == 2
    assert by_id["r-0"]["resume"] == {"kind": "lane", "step": 7}
    assert by_id["r-0"][HIVE_EPOCH_KEY] == 2
    # ONE trace spans both epochs, and the story shows the restart
    assert recovered.flights.trace_id_of("r-0") == \
        trace_ids["r-0"]
    record = recovered.flights.get("r-0")
    events = [e["event"] for e in record["events"]]
    assert events[:2] == ["submit", "grant"]
    assert "hive_recovered" in events
    grants = [e for e in record["events"] if e["event"] == "grant"]
    assert [g.get("epoch") for g in grants] == [1, 2]
    assert _counter(recovered,
                    "chiaswarm_hive_recoveries_total") == 1


def _counter(hive, name: str) -> float:
    metric = hive.metrics.get(name)
    return 0.0 if metric is None else metric.value()


def test_pre_epoch_upload_settles_once_as_epoch_salvage(tmp_path):
    clock = [0.0]
    journal = _journal(tmp_path)
    hive = _hive(journal=journal, clock=lambda: clock[0])
    hive.submit(_job("s-0"))
    clock[0] += 0.1
    hive._take_jobs("w1")
    journal.close()
    recovered = MiniHive.recover(_journal(tmp_path), lease_s=5.0,
                                 max_attempts=3,
                                 clock=lambda: clock[0])
    # the worker that rode through the crash uploads its epoch-1 work
    ack = recovered._record_result(_ok_result("s-0", "w1", epoch=1),
                                   "w1")
    assert ack == {"status": "ok"}
    assert _counter(recovered,
                    "chiaswarm_hive_epoch_salvage_total") == 1
    # settled exactly once: the second copy (either epoch) is a dup
    assert recovered._record_result(
        _ok_result("s-0", "w2", epoch=2), "w2") == {"status": "duplicate"}
    assert _counter(recovered,
                    "chiaswarm_hive_epoch_salvage_total") == 1
    record = recovered.flights.get("s-0")
    events = [e["event"] for e in record["events"]]
    assert "epoch_salvage" in events
    assert events.count("settled") == 1
    # the settle stamp names both epochs
    assert record["settled"]["epoch"] == 2


def test_epoch_handshake_rejects_stale_worker(tmp_path):
    """A heartbeat claiming a pre-restart epoch is rejected whole: no
    lease extension, no checkpoint custody, every claimed job reported
    lost, and the current epoch handed back for re-registration."""

    async def scenario():
        clock = [0.0]
        journal = _journal(tmp_path)
        hive = _hive(journal=journal, clock=lambda: clock[0])
        hive.submit(_job("h-0"))
        clock[0] += 0.1
        hive._take_jobs("w1")
        journal.close()
        recovered = MiniHive.recover(_journal(tmp_path), lease_s=5.0,
                                     max_attempts=3,
                                     clock=lambda: clock[0])
        uri = await recovered.start()
        # re-grant h-0 in the new epoch so a live lease exists
        clock[0] += 0.01
        [payload] = recovered._take_jobs("w2")
        assert payload[HIVE_EPOCH_KEY] == 2
        import aiohttp

        async with aiohttp.ClientSession() as session:
            stale_beat = {"worker_name": "w2", HIVE_EPOCH_KEY: 1,
                          "jobs": [{"id": "h-0",
                                    "checkpoint": {"step": 3}}]}
            async with session.post(f"{uri}/api/heartbeat",
                                    json=stale_beat) as response:
                stale_ack = await response.json()
            stale_custody = "h-0" in recovered.checkpoints
            fresh_beat = dict(stale_beat)
            fresh_beat[HIVE_EPOCH_KEY] = 2
            async with session.post(f"{uri}/api/heartbeat",
                                    json=fresh_beat) as response:
                fresh_ack = await response.json()
        await recovered.stop()
        return recovered, stale_ack, stale_custody, fresh_ack

    recovered, stale_ack, stale_custody, fresh_ack = \
        asyncio.run(scenario())
    assert stale_ack["status"] == "stale_epoch"
    assert stale_ack[HIVE_EPOCH_KEY] == 2
    assert stale_ack["lost"] == ["h-0"]
    # the stale beat stored NO custody and extended nothing
    assert stale_custody is False
    assert _counter(recovered,
                    "chiaswarm_hive_stale_epoch_heartbeats_total") == 1
    assert _counter(recovered,
                    "chiaswarm_hive_checkpoints_stale_total") == 1
    # the re-registered beat (current epoch) is served normally
    assert fresh_ack["status"] == "ok"
    assert fresh_ack[HIVE_EPOCH_KEY] == 2
    assert fresh_ack["lost"] == []
    assert recovered.checkpoints["h-0"] == {"step": 3}


def test_wire_parity_without_journal(tmp_path):
    """THE parity gate: a journal-less MiniHive's granted payload keeps
    exactly today's key set — no epoch stamp anywhere on the wire —
    and a journaled hive adds exactly ``hive_epoch``."""
    clock = [0.0]
    plain = _hive(clock=lambda: clock[0])
    job = _job("p-0")
    plain.submit(dict(job))
    clock[0] += 0.1
    [payload] = plain._take_jobs("w1")
    expected = set(job) | {"attempt", "queued_s", "trace_ctx"}
    assert set(payload) == expected
    assert plain.hive_epoch == 0
    # settled results keep their historical shape even when a worker
    # echoes an epoch stamp (defensively popped, never stored)
    ack = plain._record_result(_ok_result("p-0", "w1", epoch=7), "w1")
    assert ack == {"status": "ok"}
    assert HIVE_EPOCH_KEY not in plain.completed["p-0"]
    # flight-record parity: no epoch fields without a journal
    grant = [e for e in plain.flights.get("p-0")["events"]
             if e["event"] == "grant"][0]
    assert "epoch" not in grant

    journaled = _hive(journal=_journal(tmp_path),
                      clock=lambda: clock[0])
    journaled.submit(dict(job))
    clock[0] += 0.1
    [payload2] = journaled._take_jobs("w1")
    assert set(payload2) == expected | {HIVE_EPOCH_KEY}


def test_hive_session_state_machine():
    clock = [0.0]
    session = HiveSession(outage_after=3, clock=lambda: clock[0])
    assert not session.in_outage
    assert session.note_failure("poll") is False
    assert session.note_failure("upload") is False
    assert session.note_failure("poll") is True  # third flips
    assert session.in_outage and session.outages == 1
    assert session.note_failure("poll") is False  # already in outage
    clock[0] += 2.5
    assert session.note_success() is True  # heals exactly once
    assert not session.in_outage
    assert session.note_success() is False
    assert session.last_outage_s == pytest.approx(2.5)
    # a success mid-streak resets the failure ladder
    session.note_failure("poll")
    session.note_failure("poll")
    session.note_success()
    assert session.note_failure("poll") is False
    assert session.consecutive_failures == 1
    snap = session.snapshot()
    assert snap["state"] == "online" and snap["outages"] == 1


# ---------------------------------------------------------------------------
# ride-through fleet chaos (real worker, scripted executor)
# ---------------------------------------------------------------------------


def test_worker_rides_through_hive_kill_and_live_replay(tmp_path):
    """The hive dies under a live worker: the session flips to OUTAGE,
    in-flight work completes and spools, and the restarted hive (same
    port, recovered from its journal) receives every result exactly
    once via the LIVE dead-letter replay — no worker restart."""

    async def scenario():
        journal = _journal(tmp_path)
        hive = MiniHive(lease_s=30.0, delay_s=0.0, max_attempts=4,
                        journal=journal)
        uri = await hive.start()
        port = hive.port
        jobs = [_job(f"ride-{i}", chaos=["slow"]) for i in range(4)]
        for job in jobs:
            hive.submit(job)
        executor = ChaoticExecutor(slow_s=0.4)
        worker = Worker(
            settings=fleet_settings(uri, "rider"),
            pool=[StubSlot(depth=4, name="rider")],
            registry=ModelRegistry(catalog=[], allow_random=True),
            executor=executor)
        task = asyncio.create_task(worker.run())
        try:
            await asyncio.wait_for(executor.started.wait(), timeout=30)
            # SIGKILL the hive mid-everything: in-memory state is gone
            await kill_hive(hive)
            # ride-through: all four jobs complete and spool while the
            # hive is down (uploads fail; the session flips to OUTAGE)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if worker.dead_letters.depth() >= 4 \
                        and not worker._inflight:
                    break
                await asyncio.sleep(0.05)
            assert worker.dead_letters.depth() >= 4, \
                worker.hive_session.snapshot()
            assert worker.hive_session.in_outage
            assert worker.stats.hive_outages >= 1
            # restart from the journal ON THE SAME PORT: the worker
            # heals on its next poll and drains the spool live
            recovered = await restart_hive(journal, port=port,
                                           lease_s=30.0, delay_s=0.0,
                                           max_attempts=4)
            await recovered.wait_for_results(4, timeout=60)
        finally:
            worker.request_stop()
            await asyncio.wait_for(
                asyncio.gather(task, return_exceptions=True), timeout=30)
            await recovered.stop()
        return recovered, worker

    recovered, worker = asyncio.run(scenario())
    uploaded = recovered.uploaded_ids()
    assert sorted(set(uploaded)) == [f"ride-{i}" for i in range(4)]
    assert len(uploaded) == len(set(uploaded))
    assert recovered.hive_epoch == 2
    # the spooled uploads carried their epoch-1 grants: salvage counted
    assert _counter(recovered,
                    "chiaswarm_hive_epoch_salvage_total") >= 1
    # the ride-through signals: an outage, assumed-lost leases, a LIVE
    # replay (distinct from the startup path), and the healed session
    assert worker.stats.hive_outages >= 1
    assert worker.stats.leases_assumed_lost >= 1
    live = worker.metrics.get("chiaswarm_dead_letter_replayed_total")
    assert live.value(when="live") >= 4
    assert live.value(when="startup") == 0
    assert not worker.hive_session.in_outage
    assert worker._last_hive_epoch == 2
    # flight completeness across the epochs
    assert recovered.flights.verify(
        [f"ride-{i}" for i in range(4)]) == []


# ---------------------------------------------------------------------------
# THE acceptance gate: hive SIGKILL'd mid-lane, recovered from journal
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_hive_sigkill_mid_lane_recovery_gate(tmp_path, monkeypatch):
    """ISSUE 14 acceptance: 3 real-lane workers on a journaled hive;
    the hive is SIGKILL'd mid-lane (and the worker holding a
    checkpointed job dies in the same incident window), then restarted
    from its journal on the same port. Every job settles exactly once
    across both epochs, the victim's job provably resumes at step >= 1
    from the JOURNALED checkpoint, the survivors ride the outage
    through (work completes, spools, replays live), and one stitched
    flight record spans both hive epochs."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_STEP_DELAY_S", "0.08")

    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)

    def lane_job(i: int) -> dict:
        return {"id": f"dur-{i}", "model_name": "tiny",
                "prompt": f"durable prompt {i}", "seed": 1400 + i,
                "num_inference_steps": 24, "guidance_scale": 7.5,
                "height": 64, "width": 64, "content_type": "image/png"}

    async def scenario():
        journal = _journal(tmp_path)
        hive = MiniHive(lease_s=60.0, delay_s=0.01, max_jobs_per_poll=1,
                        journal=journal)
        uri = await hive.start()
        port = hive.port
        for i in range(3):
            hive.submit(lane_job(i))

        workers = []
        for tag in ("a", "b", "c"):
            pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                            devices=jax.devices()[:1])
            workers.append(Worker(
                settings=fleet_settings(uri, f"durfleet-{tag}",
                                        job_deadline_s=600.0,
                                        drain_timeout_s=30.0,
                                        result_drain_timeout_s=30.0),
                registry=registry, pool=pool))
        tasks = {w.settings.worker_name: asyncio.create_task(w.run())
                 for w in workers}
        by_name = {w.settings.worker_name: w for w in workers}
        victim = victim_job = None
        recovered = None
        try:
            # wait until a lane checkpoint (step >= 1) is JOURNALED
            # hive-side, then SIGKILL the hive mid-lane; the lease
            # holder of that job dies in the same incident window
            # (combined hive+worker failure), so its job can only come
            # back through journal recovery + redelivery-with-resume
            deadline = time.monotonic() + 240
            while victim is None and time.monotonic() < deadline:
                for job_id, ckpt in list(hive.checkpoints.items()):
                    holder = hive.lease_holder(job_id)
                    if ckpt.get("kind") == "lane" and \
                            int(ckpt.get("step", 0)) >= 1 and \
                            holder is not None:
                        victim_job, victim = job_id, holder
                        break
                if victim is None:
                    await asyncio.sleep(0.02)
            assert victim is not None, \
                f"no lane checkpoint ever journaled: {hive.stats()}"
            await kill_hive(hive)          # the hive SIGKILL
            tasks[victim].cancel()         # same-incident worker loss
            await asyncio.gather(tasks[victim], return_exceptions=True)

            # the survivors ride through: their lanes run to
            # completion against a dead hive and the results spool
            survivors = [w for w in workers
                         if w.settings.worker_name != victim]
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                if all(w.dead_letters.depth() >= 1
                       and not w._inflight for w in survivors):
                    break
                await asyncio.sleep(0.05)
            for w in survivors:
                assert w.dead_letters.depth() >= 1, (
                    w.settings.worker_name, w.hive_session.snapshot())
                assert w.stats.hive_outages >= 1

            # restart from the journal on the SAME port: survivors
            # heal, spools replay live, and the victim's checkpointed
            # job redelivers WITH resume state from the journal
            recovered = await restart_hive(journal, port=port,
                                           lease_s=60.0, delay_s=0.01,
                                           max_jobs_per_poll=1)
            await recovered.wait_for_results(3, timeout=300)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=60)
                                   for t in tasks.values()),
                                 return_exceptions=True)
            for worker in workers:
                for slot in worker.pool:
                    stepper = getattr(slot, "_stepper", None)
                    if stepper is not None:
                        stepper.shutdown()
            if recovered is not None:
                await recovered.stop()
            else:
                await hive.stop()
        return recovered, workers, by_name, victim, victim_job

    recovered, workers, by_name, victim, victim_job = \
        asyncio.run(scenario())

    # zero job loss, exactly-once settlement across both epochs
    uploaded = recovered.uploaded_ids()
    assert sorted(set(uploaded)) == ["dur-0", "dur-1", "dur-2"]
    assert len(uploaded) == len(set(uploaded))
    assert recovered.abandoned == []
    for result in recovered.results:
        assert result["pipeline_config"].get("error") is None, result
        assert "fatal_error" not in result
        assert HIVE_EPOCH_KEY not in result  # popped before storing
    assert recovered.hive_epoch == 2

    # the victim's job resumed at step >= 1 from the JOURNALED
    # checkpoint — its only possible path: the holder died with the
    # hive, so the resume state crossed the crash through the WAL
    resumed = recovered.completed[victim_job]
    assert resumed["worker_name"] != victim
    stepper_info = resumed["pipeline_config"].get("stepper") or {}
    assert int(stepper_info.get("resume_step", 0)) >= 1, stepper_info
    survivor_stats = [
        slot._stepper.stats()
        for worker in workers
        if worker.settings.worker_name != victim
        for slot in worker.pool
        if getattr(slot, "_stepper", None) is not None
    ]
    assert sum(s.get("rows_resumed", 0) for s in survivor_stats) >= 1

    # ride-through signals: outages counted, spools drained LIVE, and
    # pre-epoch uploads settled exactly once as epoch salvage
    for worker in workers:
        if worker.settings.worker_name == victim:
            continue
        assert worker.stats.hive_outages >= 1
        live = worker.metrics.get(
            "chiaswarm_dead_letter_replayed_total")
        assert live.value(when="live") >= 1
        assert worker._last_hive_epoch == 2
    assert _counter(recovered,
                    "chiaswarm_hive_epoch_salvage_total") >= 1

    # ONE stitched flight record spans both hive epochs: grant 1 in
    # epoch 1 (replayed from the journal), the restart marker, grant 2
    # in epoch 2, exactly one settle — attempt chain gapless
    assert recovered.flights.verify(["dur-0", "dur-1", "dur-2"]) == []
    record = recovered.flights.get(victim_job)
    events = [e["event"] for e in record["events"]]
    assert "hive_recovered" in events and "checkpoint" in events
    assert events.count("settled") == 1
    grants = [e for e in record["events"] if e["event"] == "grant"]
    assert [g["attempt"] for g in grants][:2] == [1, 2]
    assert {g.get("epoch") for g in grants} == {1, 2}
    assert grants[0]["worker"] == victim
    assert record["settled"]["worker"] != victim


# ---------------------------------------------------------------------------
# nightly soak: seeded kill/restart cycles across epochs
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_hive_restart_soak_exactly_once_across_epochs(tmp_path):
    """Nightly durability soak (seed = run id): a seeded job mix over a
    journaled hive with TWO mid-run hive kill/restart cycles under 3
    riding-through workers. Every issued job settles exactly once
    across three hive epochs, and every flight record is complete."""
    import os
    import random

    seed = os.environ.get("CHIASWARM_SOAK_SEED", "durable-soak-default")
    n_jobs = int(os.environ.get("CHIASWARM_SOAK_JOBS", "45"))
    rng = random.Random(f"durable-soak:{seed}")
    scripts = ([["ok"]] * 5 + [["slow"]] * 3 + [["oom", "ok"]] * 2
               + [["fetch", "ok"]] * 2 + [["crash"]] + [["fatal"]])
    jobs = [_job(f"soak-{i}", chaos=list(rng.choice(scripts)))
            for i in range(n_jobs)]
    restarts = sorted(rng.sample(range(n_jobs // 5, 4 * n_jobs // 5), 2))

    async def scenario():
        journal = _journal(tmp_path)
        hive = MiniHive(lease_s=2.0, delay_s=0.0, max_attempts=6,
                        max_jobs_per_poll=3, journal=journal)
        uri = await hive.start()
        port = hive.port
        for job in jobs:
            hive.submit(job)
        workers = [Worker(
            settings=fleet_settings(uri, f"dsoak-{tag}",
                                    job_deadline_s=0.5),
            pool=[StubSlot(name=f"dsoak-{tag}")],
            registry=ModelRegistry(catalog=[], allow_random=True),
            executor=ChaoticExecutor(hang_s=1.0, slow_s=0.1))
            for tag in ("a", "b", "c")]
        tasks = {w.settings.worker_name: asyncio.create_task(w.run())
                 for w in workers}
        cycles = 0
        try:
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                settled = len(hive.completed) + len(hive.abandoned)
                if cycles < len(restarts) and \
                        settled >= restarts[cycles]:
                    # the seeded kill/restart cycle: SIGKILL, then
                    # recover from the journal on the same port
                    await kill_hive(hive)
                    await asyncio.sleep(0.3)  # let outages flip
                    hive = await restart_hive(
                        journal, port=port, lease_s=2.0, delay_s=0.0,
                        max_attempts=6, max_jobs_per_poll=3)
                    cycles += 1
                if len(hive.completed) + len(hive.abandoned) >= n_jobs:
                    break
                hive.sweep()
                await asyncio.sleep(0.05)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=30)
                                   for t in tasks.values()),
                                 return_exceptions=True)
            await hive.stop()
        return hive, cycles

    hive, cycles = asyncio.run(scenario())
    assert cycles == 2 and hive.hive_epoch == 3
    issued = [j["id"] for j in jobs]
    completed = set(hive.completed)
    abandoned = set(hive.abandoned)
    assert completed.isdisjoint(abandoned)
    assert completed | abandoned == set(issued), \
        sorted(set(issued) - completed - abandoned)
    uploaded = hive.uploaded_ids()
    assert len(uploaded) == len(set(uploaded))
    # flight completeness across ALL epochs (the chaos-soak.yml gate)
    assert hive.flights.verify(issued, require_settled=False) == []
    assert hive.flights.verify(sorted(completed)) == []
    # the journal kept every transition durable across the cycles
    assert hive.journal.snapshot_counters()["records_written"] > 0


# ---------------------------------------------------------------------------
# journal knobs
# ---------------------------------------------------------------------------


def test_journal_env_knobs(tmp_path, monkeypatch):
    monkeypatch.setenv("CHIASWARM_HIVE_JOURNAL_SEGMENT_BYTES", "8192")
    monkeypatch.setenv("CHIASWARM_HIVE_JOURNAL_FSYNC", "0")
    monkeypatch.setenv("CHIASWARM_HIVE_JOURNAL_COMPACT_EVERY", "77")
    journal = HiveJournal(tmp_path / "env")
    assert journal.segment_bytes == 8192
    assert journal.fsync is False
    assert journal.compact_every == 77
    # explicit args beat the environment
    explicit = HiveJournal(tmp_path / "env2", segment_bytes=65536,
                           fsync=True, compact_every=0)
    assert explicit.segment_bytes == 65536
    assert explicit.fsync is True
    assert explicit.compact_every == 0


def test_epoch_sidecar_survives_compaction(tmp_path):
    clock = [0.0]
    journal = _journal(tmp_path)
    hive = _hive(journal=journal, clock=lambda: clock[0])
    hive.submit(_job("e-0"))
    hive.compact()  # epoch records pruned into the snapshot
    journal.close()
    assert _journal(tmp_path).stored_epoch() == 1
    recovered = MiniHive.recover(_journal(tmp_path),
                                 clock=lambda: clock[0])
    assert recovered.hive_epoch == 2
    recovered.journal.close()
    # a second recovery keeps climbing — epochs are monotone forever
    again = MiniHive.recover(_journal(tmp_path),
                             clock=lambda: clock[0])
    assert again.hive_epoch == 3
