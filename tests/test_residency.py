"""Residency suite (ISSUE 8): the HBM ledger, the int8 weight path, and
model churn through a real Worker.

Three tiers:

1. **Ledger units** — fake loaders, explicit budgets, a fake clock:
   reservation/hit semantics, the donation no-double-buffer peak
   assertion, priority vs LRU eviction order, the degradation rungs
   (load-per-job, model_unavailable bounce), prefetch from the arrival
   EWMA, and the budget squeeze.
2. **Quantized-vs-fp parity gates** — per diffusion family kind (tiny
   ~ sd15-shaped, tiny_xl ~ SDXL-shaped): the per-channel round-trip
   error bound, and end-to-end generated images within tolerance of the
   fp path through the real registry.
3. **E2E churn** — a real Worker serving a mixed-model job stream under
   a budget that cannot hold the catalog: zero job loss, evictions
   observed, and peak ledger bytes never exceeding budget + one model.
"""

from __future__ import annotations

import asyncio
import gc
import sys

import numpy as np
import pytest

from chiaswarm_tpu.node.resilience import classify_exception
from chiaswarm_tpu.obs.metrics import Registry
from chiaswarm_tpu.serving.residency import (
    ArrivalEwma,
    ModelUnavailable,
    ResidencyManager,
    is_transient,
)


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    """Isolate the settings root (residency.json, spools) per test."""
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


def manager(budget: int, hard: int | None = None, **over) -> ResidencyManager:
    over.setdefault("metrics_registry", Registry())
    over.setdefault("persist_path", None)
    over.setdefault("reserve_wait_s", 0.2)
    return ResidencyManager(budget_bytes=budget,
                            hard_limit_bytes=hard or budget * 2, **over)


class FakeModel:
    def __init__(self, nbytes: int) -> None:
        self.nbytes = nbytes


def loader_of(log: list, name: str, nbytes: int):
    def load():
        log.append(name)
        return FakeModel(nbytes)

    return load


def size_of(value: FakeModel) -> int:
    return value.nbytes


# ---------------------------------------------------------------------------
# 1. ledger units
# ---------------------------------------------------------------------------


def test_reservation_hit_and_measured_accounting():
    loads: list[str] = []
    m = manager(1000)
    a = m.acquire("ka", loader_of(loads, "a", 400), model="a",
                  size_of=size_of)
    assert m.acquire("ka", loader_of(loads, "a", 400), model="a",
                     size_of=size_of) is a
    assert loads == ["a"]          # the second acquire is a pure hit
    assert m.hits == 1 and m.misses == 1
    assert m.resident_bytes == 400  # measured, not estimated
    assert m.model_states()["a"] == "resident"
    assert m.measured_footprints()["a"] == 400


def test_donation_swap_never_double_buffers():
    """THE no-double-buffer invariant: with the footprint known, a swap
    evicts the victim BEFORE loading the replacement, so peak bytes stay
    within the budget; an unknown first load is allowed budget + one
    model and never more."""
    loads: list[str] = []
    m = manager(1000)
    m.acquire("ka", loader_of(loads, "a", 600), model="a", size_of=size_of)
    # first-ever load of b: footprint unknown, so the ledger may briefly
    # hold a while b loads — bounded by budget + b itself
    m.acquire("kb", loader_of(loads, "b", 700), model="b", size_of=size_of)
    assert m.resident_bytes <= 1000
    assert m.peak_bytes <= 1000 + 700
    # now both footprints are measured: the swap back to a must reserve
    # and evict FIRST — peak never exceeds the budget during this swap
    m.reset_peak()
    m.acquire("ka", loader_of(loads, "a", 600), model="a", size_of=size_of)
    assert m.resident_models() == ["a"]
    assert m.peak_bytes <= 1000, (
        f"double-buffered swap: peak {m.peak_bytes} > budget 1000")
    assert m.evictions >= 2
    assert m.model_states()["b"] == "evicted"


def test_priority_evicts_low_before_lru():
    """Eviction order is (priority, LRU): a high-priority family stays
    resident even when it is the least recently used entry."""
    loads: list[str] = []
    clock = [0.0]
    m = manager(1000, clock=lambda: clock[0])
    m.acquire("ka", loader_of(loads, "hot", 400), model="hot",
              size_of=size_of, priority=5)
    clock[0] = 1.0
    m.acquire("kb", loader_of(loads, "cold", 400), model="cold",
              size_of=size_of, priority=0)
    clock[0] = 2.0
    # c needs room: "hot" is older (LRU would evict it) but outranks
    # "cold" — cold must go first
    m.acquire("kc", loader_of(loads, "c", 400), model="c", size_of=size_of,
              priority=5)
    states = m.model_states()
    assert states["hot"] == "resident"
    assert states["cold"] == "evicted"
    # equal priorities fall back to LRU: "hot" (older) goes before "c"
    clock[0] = 3.0
    m.acquire("kd", loader_of(loads, "d", 400), model="d", size_of=size_of,
              priority=5)
    assert m.model_states()["hot"] == "evicted"
    assert m.model_states()["c"] == "resident"


def test_degraded_model_loads_per_job_and_releases():
    """The graceful-degradation rung: a model bigger than the budget
    (but within the hard limit) still serves — load -> run -> release,
    nothing admitted resident, the transient reservation freed when the
    job's references die."""
    loads: list[str] = []
    m = manager(500, hard=2000)
    value = m.acquire("kx", loader_of(loads, "x", 800), model="x",
                      size_of=size_of, estimate=lambda: 800)
    assert is_transient(value)
    assert m.resident_models() == []
    assert m.degraded_loads == 1
    assert m.model_states()["x"] == "degraded"
    assert m.reserved_bytes == 800
    del value
    gc.collect()
    assert m.reserved_bytes == 0
    # every job pays its own load — nothing was cached
    m.acquire("kx", loader_of(loads, "x", 800), model="x",
              size_of=size_of)
    assert loads == ["x", "x"]
    assert m.would_degrade("x")       # the executor's lane pre-check


def test_bounce_is_model_unavailable():
    """A model that cannot fit even transiently bounces with the
    redispatch taxonomy: classify_exception -> model_unavailable (the
    mini-hive REDISPATCH_KINDS contract, PR 6)."""
    m = manager(500, hard=1000)
    with pytest.raises(ModelUnavailable) as err:
        m.acquire("kz", loader_of([], "z", 4000), model="z",
                  estimate=lambda: 4000)
    assert classify_exception(err.value) == "model_unavailable"
    assert m.bounces == 1
    assert m.model_states()["z"] == "unavailable"


def test_budget_squeeze_evicts_immediately():
    loads: list[str] = []
    m = manager(1000)
    m.acquire("ka", loader_of(loads, "a", 400), model="a", size_of=size_of)
    m.acquire("kb", loader_of(loads, "b", 400), model="b", size_of=size_of)
    m.set_budget(450)
    assert m.resident_bytes <= 450
    assert len(m.resident_models()) == 1
    reg = m._m_evictions
    assert reg.value(reason="squeeze") >= 1


def test_prefetch_reloads_hottest_evicted_model():
    """Idle polls warm-load by demand: the evicted model with the higher
    arrival EWMA comes back first, into FREE budget only."""
    loads: list[str] = []
    m = manager(1000)
    m.acquire("ka", loader_of(loads, "a", 400), model="a", size_of=size_of)
    for _ in range(5):  # b is the hot one
        m.acquire("kb", loader_of(loads, "b", 400), model="b",
                  size_of=size_of)
    m.set_budget(100)
    m.set_budget(1000)
    assert m.resident_models() == []
    assert m.note_idle()
    deadline = 100
    while "b" not in m.resident_models() and deadline:
        deadline -= 1
        import time

        time.sleep(0.02)
    assert m.resident_models() == ["b"]
    assert m.prefetch_loads == 1
    # no free room -> no prefetch (it must never evict the working set)
    m.set_budget(400)
    assert not m.note_idle()


def test_prefetch_disabled_and_quarantine_skipped():
    loads: list[str] = []
    m = manager(1000, prefetch=False)
    m.acquire("ka", loader_of(loads, "a", 400), model="a", size_of=size_of)
    m.set_budget(100)
    m.set_budget(1000)
    assert not m.note_idle()
    m.prefetch_enabled = True
    m.note_quarantined("a")
    assert not m.note_idle()  # quarantined models never prefetch
    assert m.model_states()["a"] == "quarantined"
    m.note_unquarantined("a")
    assert m.model_states()["a"] == "evicted"


def test_failed_load_releases_reservation_and_marks_unavailable():
    m = manager(1000)

    def boom():
        raise RuntimeError("conversion exploded")

    with pytest.raises(RuntimeError):
        m.acquire("ka", boom, model="a", estimate=lambda: 400)
    assert m.reserved_bytes == 0
    assert m.model_states()["a"] == "unavailable"
    # the model is not poisoned: a later working load admits normally
    m.acquire("ka", loader_of([], "a", 400), model="a", size_of=size_of)
    assert m.model_states()["a"] == "resident"


def test_footprints_persist_across_managers(tmp_path):
    """Measured footprints survive restarts: the next manager (and the
    worker's mesh policy) plans with real numbers from load one."""
    path = tmp_path / "residency.json"
    m1 = manager(1000, persist_path=path)
    m1.acquire("ka", loader_of([], "a", 321), model="a", size_of=size_of)
    m2 = manager(1000, persist_path=path)
    assert m2.measured_footprints() == {"a": 321}
    # corrupt file: loud fallback to estimates, not a crash
    path.write_text("{not json", encoding="utf-8")
    m3 = manager(1000, persist_path=path)
    assert m3.measured_footprints() == {}


def test_arrival_ewma_decays_idle():
    ewma = ArrivalEwma(window_s=2.0)
    now = 0.0
    for _ in range(10):
        now += 0.1
        ewma.note(1, now)
    busy = ewma.rate(now)
    assert busy > 1.0
    assert ewma.rate(now + 10.0) < busy / 8


# ---------------------------------------------------------------------------
# 2. int8 quantization: units + per-family-kind forward parity gates
# ---------------------------------------------------------------------------


def test_quantize_round_trip_error_bound():
    import jax

    from chiaswarm_tpu.convert.quantize import (
        Int8Param,
        dequantize_tree,
        quantize_tree,
    )

    rng = np.random.default_rng(0)
    tree = {
        "dense": np.asarray(rng.standard_normal((128, 96)), np.float32),
        "conv": np.asarray(rng.standard_normal((3, 3, 32, 64)), np.float32),
        "bias": np.zeros((96,), np.float32),     # 1-D: stays fp
        "small": np.ones((8, 8), np.float32),    # < MIN_QUANT_SIZE: fp
    }
    q = quantize_tree(jax.tree.map(np.asarray, tree))
    assert isinstance(q["dense"], Int8Param)
    assert isinstance(q["conv"], Int8Param)
    assert not isinstance(q["bias"], Int8Param)
    assert not isinstance(q["small"], Int8Param)
    d = dequantize_tree(q)
    for key in ("dense", "conv"):
        w = tree[key]
        r = np.asarray(d[key])
        assert r.dtype == w.dtype
        scale = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)),
                       keepdims=True) / 127.0
        # round-to-nearest bound: half a code per channel
        assert np.all(np.abs(w - r) <= scale / 2 + 1e-8), key
    # the capacity claim: int8 + scales well under half the fp bytes
    q_bytes = sum(leaf.nbytes for leaf in jax.tree.leaves(q))
    fp_bytes = sum(w.nbytes for w in tree.values())
    assert q_bytes < fp_bytes * 0.5


@pytest.mark.parametrize("family", ["tiny", "tiny_xl"])
def test_int8_forward_parity_per_family_kind(family, monkeypatch):
    """The gate on the int8 path (ISSUE 8): generated images through the
    REAL registry with CHIASWARM_WEIGHTS=int8 must match the fp path
    within tolerance, per diffusion family kind (sd15-shaped and
    SDXL-shaped tiny twins)."""
    monkeypatch.setenv("CHIASWARM_STEPPER", "0")
    from chiaswarm_tpu.convert.quantize import quantized_leaf_count
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.pipelines.diffusion import GenerateRequest

    def registry():
        return ModelRegistry(
            catalog=[{"name": family, "family": family}],
            allow_random=True,
            residency=manager(1 << 30, hard=2 << 30))

    req = GenerateRequest(prompt="parity", steps=2, guidance_scale=7.5,
                          height=64, width=64, batch=1, seed=11)
    monkeypatch.delenv("CHIASWARM_WEIGHTS", raising=False)
    pipe_fp = registry().pipeline(family)
    img_fp, _ = pipe_fp(req)

    monkeypatch.setenv("CHIASWARM_WEIGHTS", "int8")
    pipe_q = registry().pipeline(family)
    assert quantized_leaf_count(pipe_q.c.params) > 0
    # the capacity multiplier, measured on the live tree
    assert pipe_q.c.param_bytes() < pipe_fp.c.param_bytes() * 0.8
    img_q, _ = pipe_q(req)

    assert img_q.shape == img_fp.shape
    diff = np.abs(img_fp.astype(np.float32) - img_q.astype(np.float32))
    rel = (np.linalg.norm(diff)
           / max(np.linalg.norm(img_fp.astype(np.float32)), 1e-9))
    assert diff.mean() < 4.0, f"mean abs uint8 diff {diff.mean():.2f}"
    assert rel < 0.05, f"relative error {rel:.4f}"


def test_int8_skipped_for_sharded_placement(monkeypatch):
    """Sharded placements stay fp: the sharding rules match fp param
    paths, so maybe_quantize_params declines multi-chip meshes."""
    import jax

    from chiaswarm_tpu.convert.quantize import (
        maybe_quantize_params,
        quantized_leaf_count,
    )
    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
    from chiaswarm_tpu.models.configs import FAMILIES

    monkeypatch.setenv("CHIASWARM_WEIGHTS", "int8")
    params = {"w": np.asarray(
        np.random.default_rng(0).standard_normal((128, 64)), np.float32)}
    family = FAMILIES["tiny"]
    mesh = build_mesh(MeshSpec({"data": 2}), devices=jax.devices()[:2])
    assert quantized_leaf_count(
        maybe_quantize_params(params, family=family, mesh=mesh)) == 0
    single = build_mesh(MeshSpec({"data": 1}), devices=jax.devices()[:1])
    assert quantized_leaf_count(
        maybe_quantize_params(params, family=family, mesh=single)) == 1


# ---------------------------------------------------------------------------
# 3. e2e: tiny-model churn through a real Worker
# ---------------------------------------------------------------------------


def _churn_registry(budget_bytes: int | None, models: list[str],
                    **manager_over):
    from chiaswarm_tpu.node.registry import ModelRegistry

    return ModelRegistry(
        catalog=[{"name": name, "family": "tiny"} for name in models],
        allow_random=True,
        residency=manager(budget_bytes or (1 << 30), **manager_over))


def _tiny_footprint() -> int:
    """Measured bytes of one resident tiny pipeline (the unit the churn
    budgets are denominated in)."""
    registry = _churn_registry(None, ["tiny/probe"])
    registry.pipeline("tiny/probe")
    return registry.residency.measured_footprints()["tiny/probe"]


def _job(job_id: str, model: str) -> dict:
    return {"id": job_id, "model_name": model, "prompt": f"p {job_id}",
            "seed": 900, "num_inference_steps": 2, "height": 64,
            "width": 64, "content_type": "image/png"}


def test_e2e_model_churn_zero_loss(monkeypatch):
    """THE churn proof (acceptance): with the budget tightened so the
    catalog cannot fit resident, a mixed-model job stream completes with
    zero job loss, evictions observed, and peak ledger bytes never
    exceeding budget + one model."""
    monkeypatch.setenv("CHIASWARM_STEPPER", "0")
    sys.path.insert(0, "tests")
    from fake_hive import FakeHive
    from test_chaos import chaos_settings

    from chiaswarm_tpu.node.worker import Worker

    footprint = _tiny_footprint()
    budget = int(footprint * 1.5)  # one model resident at a time
    models = ["tiny/a", "tiny/b"]
    registry = _churn_registry(budget, models, hard=footprint * 4)
    mgr = registry.residency
    mgr.reset_peak()

    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])

    async def scenario():
        hive = FakeHive()
        await hive.start()
        worker = Worker(
            settings=chaos_settings(hive.uri, job_deadline_s=600.0,
                                    workflow_deadline_s={}),
            registry=registry, pool=pool)
        task = asyncio.create_task(worker.run())
        try:
            # alternating models, offered ONE AT A TIME so every other
            # job deterministically forces a swap (a depth-2 slot would
            # otherwise load both models concurrently and the eviction
            # count would depend on admit order)
            for i, model in enumerate([models[0], models[1], models[0]]):
                hive.jobs.append(_job(f"churn-{i}", model))
                await hive.wait_for_results(i + 1, timeout=600)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=60)
            await hive.stop()
        return hive.results, worker

    results, worker = asyncio.run(scenario())
    by_id = {r["id"]: r for r in results}
    # zero loss, exactly once, all successes
    assert sorted(by_id) == ["churn-0", "churn-1", "churn-2"]
    for result in results:
        assert result["pipeline_config"].get("error") is None, result

    snap = mgr.snapshot()
    assert snap["evictions"] >= 2, snap        # the stream churned
    largest = max(mgr.measured_footprints().values())
    # THE no-double-buffer invariant at system scale
    assert mgr.peak_bytes <= budget + largest, (
        f"peak {mgr.peak_bytes} > budget {budget} + one model {largest}")
    assert snap["resident_bytes"] <= budget
    # the health endpoint surfaces the ledger + the state enum
    health = worker.health()
    assert health["residency"]["evictions"] >= 2
    states = health["models"]
    assert set(models) <= set(states)
    assert all(state in ("resident", "evicted", "loading", "cold")
               for state in states.values()), states


def test_e2e_degraded_model_serves_load_per_job(monkeypatch):
    """Squeeze the budget BELOW one model: jobs still complete through
    the load-per-job rung, stamped ``residency: per_job`` in the result
    config; lanes are skipped for the degraded model."""
    monkeypatch.setenv("CHIASWARM_STEPPER", "0")
    sys.path.insert(0, "tests")
    from fake_hive import FakeHive
    from test_chaos import chaos_settings

    from chiaswarm_tpu.node.worker import Worker

    footprint = _tiny_footprint()
    registry = _churn_registry(int(footprint * 0.5), ["tiny/d"],
                               hard=footprint * 4)
    # pre-teach the ledger the footprint so the FIRST job already takes
    # the degraded path (production learns it on load one)
    registry.residency._footprints["tiny/d"] = footprint

    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])

    async def scenario():
        hive = FakeHive()
        await hive.start()
        hive.jobs.append(_job("deg-0", "tiny/d"))
        worker = Worker(
            settings=chaos_settings(hive.uri, job_deadline_s=600.0,
                                    workflow_deadline_s={}),
            registry=registry, pool=pool)
        task = asyncio.create_task(worker.run())
        try:
            await hive.wait_for_results(1, timeout=600)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=60)
            await hive.stop()
        return hive.results

    [result] = asyncio.run(scenario())
    assert result["pipeline_config"].get("error") is None, result
    assert result["pipeline_config"].get("residency") == "per_job"
    assert registry.residency.degraded_loads >= 1
    assert registry.residency.resident_models() == []
    assert registry.model_states()["tiny/d"] == "degraded"


# ---------------------------------------------------------------------------
# review-hardening regressions (pre-commit code review findings)
# ---------------------------------------------------------------------------


def test_inflight_transient_does_not_starve_resident_loads():
    """A degraded load-per-job reservation in flight (held for the whole
    job) must not make concurrent resident loads evict the working set
    or bounce: transient bytes count against the HARD limit only."""
    loads: list[str] = []
    m = manager(1000, hard=5000)
    m.acquire("ka", loader_of(loads, "a", 400), model="a", size_of=size_of)
    big = m.acquire("kx", loader_of(loads, "x", 1500), model="x",
                    size_of=size_of, estimate=lambda: 1500)
    assert is_transient(big)
    assert m.reserved_bytes == 1500
    # resident load while the transient is outstanding: fits the budget,
    # must neither bounce nor evict a
    m.acquire("kb", loader_of(loads, "b", 500), model="b", size_of=size_of)
    assert m.model_states()["a"] == "resident"
    assert m.model_states()["b"] == "resident"
    assert m.bounces == 0
    assert m.evictions == 0
    del big
    gc.collect()
    assert m.reserved_bytes == 0


def test_prefetch_load_never_evicts_even_when_racing():
    """The no-churn invariant holds at RESERVATION time, not just at
    candidate selection: a prefetch whose free budget vanished in the
    race window skips instead of evicting (and counts nothing)."""
    from chiaswarm_tpu.serving.residency import _PrefetchSkip

    loads: list[str] = []
    m = manager(1000)
    m.acquire("ka", loader_of(loads, "a", 700), model="a", size_of=size_of)
    m._footprints["b"] = 600
    # simulate the race: the budget is already full when the prefetch
    # load itself runs (note_idle's selection happened "earlier")
    with pytest.raises(_PrefetchSkip):
        m._load("kb", loader_of(loads, "b", 600), model="b",
                size_of=size_of, estimate=None, priority=0,
                mode="prefetch")
    assert m.model_states()["a"] == "resident"
    assert m.evictions == 0
    assert m.prefetch_loads == 0


def test_eviction_purges_orphaned_executables():
    """Evicting a model drops its compiled executables from the bounded
    global LRU — keyed by the dead components' id, they can never hit
    again and would thrash live models' programs out of the cache."""
    from chiaswarm_tpu.core.compile_cache import GLOBAL_CACHE

    class WithComponents(FakeModel):
        def __init__(self, nbytes):
            super().__init__(nbytes)
            self.c = object()

    m = manager(1000)
    value = WithComponents(700)
    m.acquire("ka", lambda: value, model="a", size_of=size_of)
    owner = id(value.c)
    GLOBAL_CACHE.cached_executable((owner, "fake_prog", ()), lambda: "x")
    assert GLOBAL_CACHE.executables._entries.get((owner, "fake_prog", ()))
    m.acquire("kb", loader_of([], "b", 700), model="b", size_of=size_of)
    assert m.model_states()["a"] == "evicted"
    assert (owner, "fake_prog", ()) not in GLOBAL_CACHE.executables._entries


def test_eviction_requests_lane_retire_for_victim_owner():
    """ISSUE 9 satellite (ROADMAP item 4c residue): evicting a model
    asks every lane built on its components object to retire at drain,
    so HBM frees at eviction instead of after the lane idle grace.
    Lanes of OTHER models are untouched."""
    from chiaswarm_tpu.serving.stepper import StepScheduler

    class WithComponents(FakeModel):
        def __init__(self, nbytes):
            super().__init__(nbytes)
            self.c = object()

    class FakeLane:
        def __init__(self, key):
            self.key = key
            self.retire_requested = False

        def request_retire(self):
            self.retire_requested = True

    m = manager(1000)
    value = WithComponents(700)
    m.acquire("ka", lambda: value, model="a", size_of=size_of)
    owner = id(value.c)
    sched = StepScheduler()  # registers in the process-wide exit set
    victim_lane = FakeLane((owner, 64, 64, 16, "sampler", None))
    other_lane = FakeLane((id(object()), 64, 64, 16, "sampler", None))
    sched._lanes[victim_lane.key] = victim_lane
    sched._lanes[other_lane.key] = other_lane
    try:
        # loading b evicts a (budget 1000 cannot hold 700 + 700)
        m.acquire("kb", loader_of([], "b", 700), model="b",
                  size_of=size_of)
        assert m.model_states()["a"] == "evicted"
        assert victim_lane.retire_requested
        assert not other_lane.retire_requested
    finally:
        sched._lanes.clear()


def test_footprints_namespaced_by_weights_format(tmp_path, monkeypatch):
    """An int8 measurement must not size a bf16 restart's reservations
    (and vice versa): the persisted footprint file keeps one section
    per CHIASWARM_WEIGHTS format."""
    path = tmp_path / "residency.json"
    monkeypatch.delenv("CHIASWARM_WEIGHTS", raising=False)
    m_bf16 = manager(1000, persist_path=path)
    m_bf16.acquire("ka", loader_of([], "a", 800), model="a",
                   size_of=size_of)
    monkeypatch.setenv("CHIASWARM_WEIGHTS", "int8")
    m_int8 = manager(1000, persist_path=path)
    assert m_int8.measured_footprints() == {}  # bf16 bytes not reused
    m_int8.acquire("ka", loader_of([], "a", 300), model="a",
                   size_of=size_of)
    # both sections persist side by side
    monkeypatch.delenv("CHIASWARM_WEIGHTS", raising=False)
    assert manager(1000, persist_path=path).measured_footprints() == {
        "a": 800}
    monkeypatch.setenv("CHIASWARM_WEIGHTS", "int8")
    assert manager(1000, persist_path=path).measured_footprints() == {
        "a": 300}


def test_persist_path_none_disables_persistence(tmp_path):
    """Benches and hermetic tests pass ``persist_path=None`` meaning
    OFF — the manager must not fall back to the operator's real
    ``<settings root>/residency.json`` (the default-path sentinel is
    reserved for omission)."""
    from chiaswarm_tpu.node.settings import settings_root

    m = manager(1000)  # helper passes persist_path=None
    m.acquire("ka", loader_of([], "a", 400), model="a", size_of=size_of)
    assert not (settings_root() / "residency.json").exists()
    # omission (the sentinel) picks the settings-root default
    m2 = ResidencyManager(budget_bytes=1000, hard_limit_bytes=2000,
                          metrics_registry=Registry())
    assert m2._persist_path == settings_root() / "residency.json"


def test_concurrent_resident_loads_wait_instead_of_bouncing():
    """Two models whose footprints each fit the budget (but not both)
    demanded concurrently must BOTH load — the second reservation waits
    for the first to settle into an evictable entry, then swaps; no
    spurious model_unavailable bounce, no fatal error."""
    import threading
    import time as _time

    m = manager(1000, hard=2000, reserve_wait_s=5.0)
    m._footprints.update({"a": 600, "b": 600})  # both known, both fit
    gate = threading.Event()

    def slow_loader(name):
        def load():
            gate.wait(timeout=10)  # hold the reservation open
            return FakeModel(600)

        return load

    results: dict[str, object] = {}

    def job(name):
        results[name] = m.acquire(
            f"k{name}", slow_loader(name), model=name, size_of=size_of)

    threads = [threading.Thread(target=job, args=(name,))
               for name in ("a", "b")]
    for thread in threads:
        thread.start()
    _time.sleep(0.1)
    gate.set()
    for thread in threads:
        thread.join(timeout=30)
    assert set(results) == {"a", "b"}
    assert m.bounces == 0
    assert not any(is_transient(v) for v in results.values())
    # one of them was swapped out to admit the other
    assert m.evictions >= 1
    assert m.resident_bytes <= 1000


def test_demand_admit_survives_concurrent_reservation_pressure():
    """A first-ever demand load whose measured footprint cannot be
    evicted for (concurrent reservations hold the budget) must still
    ADMIT — the memory is already allocated; refusing would fail a
    healthy job with an internal error."""
    import threading
    import time as _time

    m = manager(1000, hard=2000, reserve_wait_s=0.3)
    m._footprints["big"] = 900
    gate = threading.Event()

    def slow_big():
        gate.wait(timeout=10)
        return FakeModel(900)

    holder = threading.Thread(target=lambda: m.acquire(
        "kbig", slow_big, model="big", size_of=size_of))
    holder.start()
    _time.sleep(0.1)  # big's 900-byte resident reservation is in flight
    # first-ever load of small (no estimate -> reserves 0): its admit
    # pass finds nothing evictable, must not raise
    value = m.acquire("ksmall", lambda: FakeModel(500), model="small",
                      size_of=size_of)
    assert isinstance(value, FakeModel)
    assert m.model_states()["small"] == "resident"
    gate.set()
    holder.join(timeout=30)
    assert m.bounces == 0
