"""End-to-end production load path at the REAL SD1.5 layout, offline.

VERDICT r3 item #2: conversion was tested per-module and rendering from
``Components.random`` — but the path a real node exercises (safetensors
snapshot on disk -> registry conversion/load -> jitted render -> artifact
envelope, the equivalent of the reference's
``DiffusionPipeline.from_pretrained`` + callback + ``make_result`` chain,
swarm/diffusion/diffusion_func.py:41-96 + swarm/output_processor.py) had
never run as ONE piece. This test authors a full SD1.5-layout snapshot on
disk — real tensor names (text tower named by transformers' own
CLIPTextModel at the published config; UNet/VAE in the diffusers naming
the converter round-trip suite pins), real shapes, safetensors, a CLIP
vocab.json/merges.txt — then runs the production path end to end and
checks the converted text tower against the torch oracle INSIDE the
loaded pipeline.

Slow tier: full-config SD1.5 on the CPU test platform is compile-heavy.
The weights-gated image-level PSNR proof stays in test_real_checkpoint.py.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

pytestmark = pytest.mark.slow

_SD15_CLIP_L = dict(vocab_size=49408, hidden_size=768,
                    intermediate_size=3072, num_hidden_layers=12,
                    num_attention_heads=12, max_position_embeddings=77,
                    hidden_act="quick_gelu", projection_dim=768)


def _write_clip_tokenizer(model_root) -> None:
    """A coherent mini CLIP-BPE vocab at the REAL special-token ids (the
    49408-row embedding's BOS/EOS rows must be hit by real encodes)."""
    merges = [("h", "i</w>"), ("c", "a"), ("ca", "t</w>")]
    tokens = {"<|startoftext|>": 49406, "<|endoftext|>": 49407}
    body = (["hi</w>", "cat</w>", "h", "i</w>", "c", "a", "t</w>"]
            + [chr(c) for c in range(ord("a"), ord("z") + 1)]
            + [chr(c) + "</w>" for c in range(ord("a"), ord("z") + 1)])
    for i, tok in enumerate(body):
        tokens.setdefault(tok, i)
    tok_dir = model_root / "tokenizer"
    tok_dir.mkdir(parents=True, exist_ok=True)
    with open(tok_dir / "vocab.json", "w", encoding="utf-8") as fh:
        json.dump(tokens, fh)
    with open(tok_dir / "merges.txt", "w", encoding="utf-8") as fh:
        fh.write("#version: 0.2\n")
        for a, b in merges:
            fh.write(f"{a} {b}\n")


def test_sd15_snapshot_to_artifact_envelope(tmp_path, monkeypatch):
    from safetensors.numpy import save_file

    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec
    from chiaswarm_tpu.models.configs import SD15
    from chiaswarm_tpu.node.executor import synchronous_do_work
    from chiaswarm_tpu.node.registry import ModelRegistry, model_dir
    from chiaswarm_tpu.pipelines.components import Components

    from tests.torch_export import export_unet, export_vae

    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    name = "runwayml/stable-diffusion-v1-5"
    root = model_dir(name)

    # ---- author the snapshot: real layout, random values ---------------
    torch.manual_seed(0)
    text_model = transformers.CLIPTextModel(
        transformers.CLIPTextConfig(**_SD15_CLIP_L)).eval()
    (root / "text_encoder").mkdir(parents=True)
    save_file({k: v.detach().numpy()
               for k, v in text_model.state_dict().items()},
              str(root / "text_encoder" / "model.safetensors"))

    src = Components.random_host(SD15, seed=0)
    for sub, state in (
        ("unet", export_unet(src.params["unet"], 4)),
        ("vae", export_vae(src.params["vae"], 4)),
    ):
        (root / sub).mkdir(parents=True)
        save_file({k: np.ascontiguousarray(np.asarray(v, np.float32))
                   for k, v in state.items()},
                  str(root / sub / "diffusion_pytorch_model.safetensors"))
    _write_clip_tokenizer(root)
    del src

    # ---- production path: registry conversion/load ---------------------
    registry = ModelRegistry(
        catalog=[{"name": name, "family": "sd15"}], allow_random=False)
    pipe = registry.pipeline(name)
    comps = pipe.c

    # the loaded tokenizer is the real CLIP BPE over the snapshot's files
    ids = comps.tokenizers[0].encode("hi cat")
    assert ids[0] == 49406 and 49407 in ids[1:]

    # converted text tower vs the torch oracle INSIDE the loaded pipeline
    # (non-circular: transformers authored these tensors and their names)
    batch = np.asarray([ids], np.int64)
    with torch.no_grad():
        want = text_model(torch.from_numpy(batch)).last_hidden_state.numpy()
    got, _ = comps.text_encoders[0].apply(
        jax.tree.map(lambda a: np.asarray(a, np.float32),
                     comps.params["text_encoder_0"]),
        batch.astype(np.int32))
    np.testing.assert_allclose(np.asarray(got, np.float32), want,
                               atol=2e-2, rtol=2e-2)  # bf16 params

    # ---- jitted render -> artifact envelope (the executor's own path) --
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])
    job = {"id": "e2e-1", "model_name": name, "prompt": "hi cat",
           "seed": 7, "num_inference_steps": 2, "height": 256,
           "width": 256, "content_type": "image/jpeg"}
    result = synchronous_do_work(job, pool.slots[0], registry)

    cfg = result["pipeline_config"]
    assert "error" not in cfg, cfg
    art = result["artifacts"]["primary"]
    assert art["content_type"] == "image/jpeg"
    assert art["blob"] and art["thumbnail"] and art["sha256_hash"]
    assert cfg["model_name"] == name and cfg["seed"] == 7

    # determinism: the same job renders byte-identical artifacts
    again = synchronous_do_work(dict(job), pool.slots[0], registry)
    assert again["artifacts"]["primary"]["sha256_hash"] == art["sha256_hash"]
