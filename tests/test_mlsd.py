"""M-LSD detector tests: torch-reference fidelity + preprocessor wiring.

The reference's mlsd mode runs controlnet_aux's MLSDdetector — the
mlsd_pytorch ``MobileV2_MLSD_Large`` graph (swarm/controlnet/
input_processor.py:17-60 dispatch); these pin the native port
(models/mlsd.py) to the same graph: MobileNetV2 trunk (4-ch input, FPN
taps), TypeA/B/C decoder, align-corners bilinear, TP-map slice, and the
line decode.
"""

from __future__ import annotations

import numpy as np
import pytest

from chiaswarm_tpu.models.mlsd import MLSDDetector, decode_lines


def _torch_mlsd():
    """Independent torch construction of MobileV2_MLSD_Large."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn
    import torch.nn.functional as F

    class ConvBNReLU(nn.Sequential):
        def __init__(self, cin, cout, k=3, stride=1, groups=1):
            super().__init__(
                nn.Conv2d(cin, cout, k, stride, (k - 1) // 2, groups=groups,
                          bias=False),
                nn.BatchNorm2d(cout), nn.ReLU6(inplace=True))

    class InvertedResidual(nn.Module):
        def __init__(self, inp, oup, stride, expand_ratio):
            super().__init__()
            hidden = inp * expand_ratio
            self.use_res = stride == 1 and inp == oup
            layers = []
            if expand_ratio != 1:
                layers.append(ConvBNReLU(inp, hidden, k=1))
            layers.extend([
                ConvBNReLU(hidden, hidden, stride=stride, groups=hidden),
                nn.Conv2d(hidden, oup, 1, 1, 0, bias=False),
                nn.BatchNorm2d(oup)])
            self.conv = nn.Sequential(*layers)

        def forward(self, x):
            return x + self.conv(x) if self.use_res else self.conv(x)

    class MobileNetV2(nn.Module):
        def __init__(self):
            super().__init__()
            plan = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
                    (6, 64, 4, 2), (6, 96, 3, 1)]
            features = [ConvBNReLU(4, 32, stride=2)]
            cin = 32
            for t, c, n, s in plan:
                for j in range(n):
                    features.append(
                        InvertedResidual(cin, c, s if j == 0 else 1, t))
                    cin = c
            self.features = nn.Sequential(*features)
            self.fpn_selected = [1, 3, 6, 10, 13]

        def forward(self, x):
            outs = []
            for i, f in enumerate(self.features):
                x = f(x)
                if i in self.fpn_selected:
                    outs.append(x)
            return outs

    class BlockTypeA(nn.Module):
        def __init__(self, in_c1, in_c2, out_c1, out_c2, upscale=True):
            super().__init__()
            self.conv1 = nn.Sequential(
                nn.Conv2d(in_c2, out_c2, 1), nn.BatchNorm2d(out_c2),
                nn.ReLU(inplace=True))
            self.conv2 = nn.Sequential(
                nn.Conv2d(in_c1, out_c1, 1), nn.BatchNorm2d(out_c1),
                nn.ReLU(inplace=True))
            self.upscale = upscale

        def forward(self, a, b):
            b = self.conv1(b)
            a = self.conv2(a)
            if self.upscale:
                b = F.interpolate(b, scale_factor=2.0, mode="bilinear",
                                  align_corners=True)
            return torch.cat((a, b), dim=1)

    class BlockTypeB(nn.Module):
        def __init__(self, in_c, out_c):
            super().__init__()
            self.conv1 = nn.Sequential(
                nn.Conv2d(in_c, in_c, 3, padding=1), nn.BatchNorm2d(in_c),
                nn.ReLU())
            self.conv2 = nn.Sequential(
                nn.Conv2d(in_c, out_c, 3, padding=1),
                nn.BatchNorm2d(out_c))

        def forward(self, x):
            return self.conv2(self.conv1(x) + x)

    class BlockTypeC(nn.Module):
        def __init__(self, in_c, out_c):
            super().__init__()
            self.conv1 = nn.Sequential(
                nn.Conv2d(in_c, in_c, 3, padding=5, dilation=5),
                nn.BatchNorm2d(in_c), nn.ReLU())
            self.conv2 = nn.Sequential(
                nn.Conv2d(in_c, in_c, 3, padding=1),
                nn.BatchNorm2d(in_c), nn.ReLU())
            self.conv3 = nn.Conv2d(in_c, out_c, 1)

        def forward(self, x):
            return self.conv3(self.conv2(self.conv1(x)))

    class MLSD(nn.Module):
        def __init__(self):
            super().__init__()
            self.backbone = MobileNetV2()
            self.block15 = BlockTypeA(64, 96, 64, 64, upscale=False)
            self.block16 = BlockTypeB(128, 64)
            self.block17 = BlockTypeA(32, 64, 64, 64)
            self.block18 = BlockTypeB(128, 64)
            self.block19 = BlockTypeA(24, 64, 64, 64)
            self.block20 = BlockTypeB(128, 64)
            self.block21 = BlockTypeA(16, 64, 64, 64)
            self.block22 = BlockTypeB(128, 64)
            self.block23 = BlockTypeC(64, 16)

        def forward(self, x):
            c1, c2, c3, c4, c5 = self.backbone(x)
            x = self.block16(self.block15(c4, c5))
            x = self.block18(self.block17(c3, x))
            x = self.block20(self.block19(c2, x))
            x = self.block22(self.block21(c1, x))
            return self.block23(x)[:, 7:, :, :]

    torch.manual_seed(0)
    net = MLSD().eval()
    # randomize BN running stats so fidelity covers them too
    with torch.no_grad():
        for m in net.modules():
            if isinstance(m, nn.BatchNorm2d):
                m.running_mean.normal_(0.0, 0.2)
                m.running_var.uniform_(0.5, 1.5)
    return torch, net


def test_conversion_matches_torch_reference():
    torch, net = _torch_mlsd()
    import jax.numpy as jnp

    from chiaswarm_tpu.convert.torch_to_flax import convert_mlsd

    state = {k: v.detach().numpy() for k, v in net.state_dict().items()}
    det = MLSDDetector(params=convert_mlsd(state))
    x = np.random.RandomState(0).rand(1, 64, 64, 4).astype(np.float32) * 2 - 1
    with torch.no_grad():
        tout = net(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    fout = np.asarray(det._fwd(det.params, jnp.asarray(x)))
    np.testing.assert_allclose(fout.transpose(0, 3, 1, 2), tout,
                               atol=5e-4, rtol=5e-3)


def test_converter_rejects_wrong_state():
    from chiaswarm_tpu.convert.torch_to_flax import convert_mlsd

    with pytest.raises(ValueError, match="expected 13"):
        convert_mlsd({"backbone.features.0.0.weight":
                      np.zeros((32, 4, 3, 3), np.float32)})


def test_decode_lines_extracts_planted_segment():
    """A synthetic TP map with one confident center and a known
    displacement must decode to exactly that segment (2x coords)."""
    tp = np.zeros((64, 64, 9), np.float32)
    tp[:, :, 0] = -10.0       # background logit ~ 0 probability
    tp[30, 20, 0] = 10.0      # one confident center at (y=30, x=20)
    tp[30, 20, 1:5] = [-8.0, -6.0, 8.0, 6.0]  # endpoints +-(8, 6)
    lines = decode_lines(tp, score_thr=0.1, dist_thr=5.0)
    assert lines.shape == (1, 4)
    np.testing.assert_allclose(lines[0], [(20 - 8) * 2, (30 - 6) * 2,
                                          (20 + 8) * 2, (30 + 6) * 2])


def test_decode_lines_threshold_is_map_space_direct():
    """pred_lines compares map-resolution length directly against dist_thr
    (no /2): a segment of map length 4 survives dist_thr=3 but not
    dist_thr=5 — the /2 variant would have kept it at dist_thr=5."""
    tp = np.zeros((64, 64, 9), np.float32)
    tp[:, :, 0] = -10.0
    tp[30, 20, 0] = 10.0
    tp[30, 20, 1:5] = [-1.6, -1.2, 1.6, 1.2]  # map length = hypot(3.2, 2.4) = 4
    assert decode_lines(tp, score_thr=0.1, dist_thr=3.0).shape == (1, 4)
    assert decode_lines(tp, score_thr=0.1, dist_thr=5.0).shape == (0, 4)


def test_detector_runs_on_odd_sizes():
    det = MLSDDetector.random(seed=0, canvas=64)
    img = (np.random.RandomState(1).rand(37, 53, 3) * 255).astype(np.uint8)
    out = det(img)
    assert out.shape == (37, 53) and out.dtype == np.uint8


def test_mlsd_uses_model_when_weights_present(monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setattr(wl, "_MLSD", [MLSDDetector.random(seed=2,
                                                          canvas=64)])
    out = wl.preprocess_image(Image.new("RGB", (64, 48), (90, 120, 40)),
                              {"type": "mlsd", "preprocess": True})
    assert np.asarray(out).shape == (48, 64, 3)


def test_mlsd_falls_back_without_weights(tmp_path, monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    monkeypatch.setattr(wl, "_MLSD", [])
    out = wl.preprocess_image(Image.new("RGB", (64, 48), (90, 120, 40)),
                              {"type": "mlsd", "preprocess": True})
    assert np.asarray(out).shape == (48, 64, 3)
    assert wl._MLSD == [None]  # stand-in path cached
