"""Static HLO cost model of tools/op_roofline.py: conv/dot/flash FLOPs
and HBM byte estimates from scheduled-HLO text (operands printed as bare
%names, shapes resolved through the definition map)."""

import importlib.util
import os

spec = importlib.util.spec_from_file_location(
    "op_roofline",
    os.path.join(os.path.dirname(__file__), "..", "tools",
                 "op_roofline.py"))
roofline = importlib.util.module_from_spec(spec)
spec.loader.exec_module(roofline)


_HLO = """\
HloModule jit_fn, is_scheduled=true

%fused_computation.7 (param_0.1: bf16[2,64,64,320], param_1.2: bf16[3,3,320,640]) -> bf16[2,64,64,640] {
  %param_0.1 = bf16[2,64,64,320]{3,2,1,0:T(8,128)(2,1)} parameter(0)
  %param_1.2 = bf16[3,3,320,640]{3,2,1,0:T(8,128)(2,1)} parameter(1)
  ROOT %convolution.9 = bf16[2,64,64,640]{3,2,1,0:T(8,128)(2,1)} convolution(%param_0.1, %param_1.2), window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f
}

%fused_computation.8 (p0: bf16[2,4096,640], p1: bf16[640,640]) -> bf16[2,4096,640] {
  %p0 = bf16[2,4096,640]{2,1,0:T(8,128)(2,1)} parameter(0)
  %p1 = bf16[640,640]{1,0:T(8,128)(2,1)} parameter(1)
  ROOT %dot.3 = bf16[2,4096,640]{2,1,0:T(8,128)(2,1)} dot(%p0, %p1), lhs_batch_dims={}, lhs_contracting_dims={2}, rhs_contracting_dims={0}
}

ENTRY %main (a: bf16[2,64,64,320], w: bf16[3,3,320,640]) -> bf16[2,64,64,640] {
  %a = bf16[2,64,64,320]{3,2,1,0:T(8,128)(2,1)} parameter(0)
  %w = bf16[3,3,320,640]{3,2,1,0:T(8,128)(2,1)} parameter(1)
  %pad.1 = f32[8,4096,128]{2,1,0:T(8,128)} parameter(2)
  %conv_fusion.1 = bf16[2,64,64,640]{3,2,1,0:T(8,128)(2,1)} fusion(%a, %w), kind=kOutput, calls=%fused_computation.7
  %x = bf16[2,4096,640]{2,1,0:T(8,128)(2,1)} parameter(3)
  %m = bf16[640,640]{1,0:T(8,128)(2,1)} parameter(4)
  %dot_fusion.2 = bf16[2,4096,640]{2,1,0:T(8,128)(2,1)} fusion(%x, %m), kind=kOutput, calls=%fused_computation.8
  %flash_attention = f32[8,4096,128]{2,1,0:T(8,128)S(1)} custom-call(%pad.1, %pad.1, %pad.1), custom_call_target="tpu_custom_call", operand_layout_constraints={f32[8,4096,128]{2,1,0}, f32[8,4096,128]{2,1,0}, f32[8,4096,128]{2,1,0}}
  ROOT %out = bf16[2,64,64,640]{3,2,1,0:T(8,128)(2,1)} fusion(%conv_fusion.1), kind=kLoop, calls=%fused_computation.7
}
"""


def test_conv_fusion_flops_and_bytes():
    costs = roofline.parse_hlo_text(_HLO)
    conv = costs["conv_fusion.1"]
    # 2 * out_elems * window * Cin = 2 * (2*64*64*640) * 9 * 320
    assert conv["flops"] == 2 * (2 * 64 * 64 * 640) * 9 * 320
    assert conv["kind"] == "conv"
    # bytes: result + a + w, bf16
    expect = 2 * (2 * 64 * 64 * 640 + 2 * 64 * 64 * 320 + 3 * 3 * 320 * 640)
    assert conv["bytes"] == expect


def test_dot_fusion_flops():
    costs = roofline.parse_hlo_text(_HLO)
    dot = costs["dot_fusion.2"]
    # 2 * out_elems * K = 2 * (2*4096*640) * 640
    assert dot["flops"] == 2 * (2 * 4096 * 640) * 640
    assert dot["kind"] == "dot"


def test_flash_custom_call_flops():
    costs = roofline.parse_hlo_text(_HLO)
    fl = costs["flash_attention"]
    # 4 * BH * L * S * D from the folded (B*H, L_pad, D) operands
    assert fl["flops"] == 4 * 8 * 4096 * 4096 * 128
    assert fl["kind"] == "flash"
    # bytes resolve through the definition map (operands are bare %names):
    # f32 result + three f32 operands
    assert fl["bytes"] == 4 * (8 * 4096 * 128) * 4


def test_operand_scan_stops_at_list_close():
    shapes = roofline._operand_shapes(
        "  %f = bf16[4,4]{1,0:T(8,128)(2,1)} fusion(%a, %b), kind=kLoop, "
        "calls=%c", "fusion",
        {"a": ("bf16", [4, 4]), "b": ("f32", [2, 2]),
         "c": ("f32", [9, 9])})
    assert shapes == [("bf16", [4, 4]), ("f32", [2, 2])]
