"""Static HLO cost model (chiaswarm_tpu/obs/hlocost.py, extracted from
tools/op_roofline.py in ISSUE 11): conv/dot/flash FLOPs and HBM byte
estimates from scheduled-HLO text (operands printed as bare %names,
shapes resolved through the definition map), while-body step folding,
and the static whole-program roofline report BENCH stamps — all costed
from canned fixtures, no TPU or jax.profiler needed."""

import pytest

from chiaswarm_tpu.obs import hlocost


_HLO = """\
HloModule jit_fn, is_scheduled=true

%fused_computation.7 (param_0.1: bf16[2,64,64,320], param_1.2: bf16[3,3,320,640]) -> bf16[2,64,64,640] {
  %param_0.1 = bf16[2,64,64,320]{3,2,1,0:T(8,128)(2,1)} parameter(0)
  %param_1.2 = bf16[3,3,320,640]{3,2,1,0:T(8,128)(2,1)} parameter(1)
  ROOT %convolution.9 = bf16[2,64,64,640]{3,2,1,0:T(8,128)(2,1)} convolution(%param_0.1, %param_1.2), window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f
}

%fused_computation.8 (p0: bf16[2,4096,640], p1: bf16[640,640]) -> bf16[2,4096,640] {
  %p0 = bf16[2,4096,640]{2,1,0:T(8,128)(2,1)} parameter(0)
  %p1 = bf16[640,640]{1,0:T(8,128)(2,1)} parameter(1)
  ROOT %dot.3 = bf16[2,4096,640]{2,1,0:T(8,128)(2,1)} dot(%p0, %p1), lhs_batch_dims={}, lhs_contracting_dims={2}, rhs_contracting_dims={0}
}

ENTRY %main (a: bf16[2,64,64,320], w: bf16[3,3,320,640]) -> bf16[2,64,64,640] {
  %a = bf16[2,64,64,320]{3,2,1,0:T(8,128)(2,1)} parameter(0)
  %w = bf16[3,3,320,640]{3,2,1,0:T(8,128)(2,1)} parameter(1)
  %pad.1 = f32[8,4096,128]{2,1,0:T(8,128)} parameter(2)
  %conv_fusion.1 = bf16[2,64,64,640]{3,2,1,0:T(8,128)(2,1)} fusion(%a, %w), kind=kOutput, calls=%fused_computation.7
  %x = bf16[2,4096,640]{2,1,0:T(8,128)(2,1)} parameter(3)
  %m = bf16[640,640]{1,0:T(8,128)(2,1)} parameter(4)
  %dot_fusion.2 = bf16[2,4096,640]{2,1,0:T(8,128)(2,1)} fusion(%x, %m), kind=kOutput, calls=%fused_computation.8
  %flash_attention = f32[8,4096,128]{2,1,0:T(8,128)S(1)} custom-call(%pad.1, %pad.1, %pad.1), custom_call_target="tpu_custom_call", operand_layout_constraints={f32[8,4096,128]{2,1,0}, f32[8,4096,128]{2,1,0}, f32[8,4096,128]{2,1,0}}
  ROOT %out = bf16[2,64,64,640]{3,2,1,0:T(8,128)(2,1)} fusion(%conv_fusion.1), kind=kLoop, calls=%fused_computation.7
}
"""

# a scheduled module with a while loop: the denoise-scan shape — the
# body's fusion must fold by the step count in the static report, the
# entry-scope fusion must not
_HLO_WHILE = """\
HloModule jit_loop, is_scheduled=true

%body_dot (p0: f32[128,128], p1: f32[128,128]) -> f32[128,128] {
  %p0 = f32[128,128]{1,0} parameter(0)
  %p1 = f32[128,128]{1,0} parameter(1)
  ROOT %dot.1 = f32[128,128]{1,0} dot(%p0, %p1), lhs_batch_dims={}, lhs_contracting_dims={1}, rhs_contracting_dims={0}
}

%while_body (arg: f32[128,128]) -> f32[128,128] {
  %arg = f32[128,128]{1,0} parameter(0)
  ROOT %step_fusion = f32[128,128]{1,0} fusion(%arg, %arg), kind=kOutput, calls=%body_dot
}

%while_cond (arg: f32[128,128]) -> pred[] {
  %arg = f32[128,128]{1,0} parameter(0)
  ROOT %lt = pred[] parameter(1)
}

ENTRY %main (x: f32[128,128], y: f32[128,128]) -> f32[128,128] {
  %x = f32[128,128]{1,0} parameter(0)
  %y = f32[128,128]{1,0} parameter(1)
  %prologue_fusion = f32[128,128]{1,0} fusion(%x, %y), kind=kOutput, calls=%body_dot
  ROOT %loop = f32[128,128]{1,0} while(%prologue_fusion), condition=%while_cond, body=%while_body
}
"""


def test_conv_fusion_flops_and_bytes():
    costs = hlocost.parse_hlo_text(_HLO)
    conv = costs["conv_fusion.1"]
    # 2 * out_elems * window * Cin = 2 * (2*64*64*640) * 9 * 320
    assert conv["flops"] == 2 * (2 * 64 * 64 * 640) * 9 * 320
    assert conv["kind"] == "conv"
    # bytes: result + a + w, bf16
    expect = 2 * (2 * 64 * 64 * 640 + 2 * 64 * 64 * 320 + 3 * 3 * 320 * 640)
    assert conv["bytes"] == expect
    assert conv["computation"] == "main"


def test_dot_fusion_flops():
    costs = hlocost.parse_hlo_text(_HLO)
    dot = costs["dot_fusion.2"]
    # 2 * out_elems * K = 2 * (2*4096*640) * 640
    assert dot["flops"] == 2 * (2 * 4096 * 640) * 640
    assert dot["kind"] == "dot"


def test_flash_custom_call_flops():
    costs = hlocost.parse_hlo_text(_HLO)
    fl = costs["flash_attention"]
    # 4 * BH * L * S * D from the folded (B*H, L_pad, D) operands
    assert fl["flops"] == 4 * 8 * 4096 * 4096 * 128
    assert fl["kind"] == "flash"
    # bytes resolve through the definition map (operands are bare %names):
    # f32 result + three f32 operands
    assert fl["bytes"] == 4 * (8 * 4096 * 128) * 4


def test_operand_scan_stops_at_list_close():
    shapes = hlocost.operand_shapes(
        "  %f = bf16[4,4]{1,0:T(8,128)(2,1)} fusion(%a, %b), kind=kLoop, "
        "calls=%c", "fusion",
        {"a": ("bf16", [4, 4]), "b": ("f32", [2, 2]),
         "c": ("f32", [9, 9])})
    assert shapes == [("bf16", [4, 4]), ("f32", [2, 2])]


def test_while_body_computations_detected():
    assert hlocost.while_body_computations(_HLO_WHILE) == {
        "while_body", "while_cond"}
    assert hlocost.while_body_computations(_HLO) == set()


def test_static_report_folds_while_body_by_steps():
    """The denoise-scan shape: the body fusion counts ``steps`` times,
    the prologue once — so a 30-step program's modeled work is
    30x body + 1x prologue, not 2 fusions."""
    dot_flops = 2 * 128 * 128 * 128
    report = hlocost.static_program_report(
        _HLO_WHILE, steps=30, peak_tflops=100.0, peak_gbps=800.0)
    assert report["steps_folded"] == 30
    expect_flops = dot_flops * (30 + 1)
    # the report rounds to 3 decimals; compare at that resolution
    assert report["modeled_gflop"] == pytest.approx(
        expect_flops / 1e9, abs=5e-4)
    by_name = {r["name"]: r for r in report["heaviest"]}
    assert by_name["step_fusion"]["count"] == 30
    assert by_name["prologue_fusion"]["count"] == 1
    assert report["roofline_bound_s"] > 0
    assert report["bound"] in ("flops", "hbm")

    # achieved time turns the bound into attainment
    measured = hlocost.static_program_report(
        _HLO_WHILE, steps=30, peak_tflops=100.0, peak_gbps=800.0,
        achieved_s=report["roofline_bound_s"] * 2)
    assert measured["attainment_pct"] == pytest.approx(50.0, abs=0.1)


def test_attainment_rows_join_and_container_exclusion():
    """The measured join: profiler durations x static costs; while/call
    container events are excluded so time is never double-booked."""
    costs = hlocost.parse_hlo_text(_HLO)
    times = {
        "conv_fusion.1": {"total_ps": 2_000_000_000, "count": 2},  # 2 ms
        "while.1": {"total_ps": 50_000_000_000, "count": 1},  # container
        "unknown_op": {"total_ps": 1_000_000_000, "count": 1},  # 1 ms
    }
    rows = hlocost.attainment_rows(times, costs, peak_tflops=100.0,
                                   peak_gbps=800.0)
    names = [r["name"] for r in rows]
    assert "while.1" not in names
    conv = next(r for r in rows if r["name"] == "conv_fusion.1")
    assert conv["count"] == 2 and conv["kind"] == "conv"
    assert conv["gflop"] == pytest.approx(
        2 * 2 * (2 * 64 * 64 * 640) * 9 * 320 / 1e9)
    # share excludes the container's span
    assert sum(r["share_pct"] for r in rows) == pytest.approx(100.0)

    summary = hlocost.conv_attainment_summary(rows)
    assert summary["conv_ms"] == pytest.approx(2.0)
    assert summary["miscosted_fusions"] >= 0


def test_op_roofline_cli_is_a_thin_shim():
    """tools/op_roofline.py now imports the library instead of owning a
    fork of the parser — the CLI module must expose the SAME objects."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "op_roofline",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "op_roofline.py"))
    roofline = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(roofline)
    assert roofline.parse_hlo_text is hlocost.parse_hlo_text
    assert roofline.collect_op_times is hlocost.collect_op_times
    assert roofline.attainment_rows is hlocost.attainment_rows


def test_program_capture_keys_by_signature():
    """ProgramCapture recompiles per input-shape signature (a lattice
    program reused across widths must not call a stale executable)."""
    import jax.numpy as jnp

    cap = hlocost.ProgramCapture()
    wrapped = cap.capturing_toplevel_jit(lambda x: x * 2)
    a = wrapped(jnp.ones((2, 2)))
    b = wrapped(jnp.ones((2, 2)))
    assert len(cap.executables) == 1  # same signature: one compile
    c = wrapped(jnp.ones((4, 4)))
    assert len(cap.executables) == 2  # new signature: fresh compile
    assert a.shape == b.shape == (2, 2) and c.shape == (4, 4)
    hlo = cap.largest_hlo()
    assert hlo and "HloModule" in hlo
    assert len(cap.mark()) == 2 and cap.mark() == []


# ------------------- swarmproof compiled-side contracts (ISSUE 15):
# analysis/hlocheck.py audits lowered programs against declared
# collective/dtype/donation contracts — same canned-fixture stance,
# no jax needed.

from chiaswarm_tpu.analysis import hlocheck


_HLO_RING = """\
HloModule jit_ring, input_output_alias={ {}: (0, {}, may-alias), {1}: (2, {}) }, is_scheduled=true

ENTRY %main (q: f32[2,8,128], k: f32[2,8,128], v: f32[2,8,128]) -> f32[2,8,128] {
  %q = f32[2,8,128]{2,1,0} parameter(0)
  %k = f32[2,8,128]{2,1,0} parameter(1)
  %v = f32[2,8,128]{2,1,0} parameter(2)
  %cp.1 = f32[2,8,128]{2,1,0} collective-permute(%k), channel_id=1, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}
  %cp-start.2 = f32[2,8,128]{2,1,0} collective-permute-start(%v), channel_id=2, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}
  %cp-done.2 = f32[2,8,128]{2,1,0} collective-permute-done(%cp-start.2)
  %scores = f32[2,8,8]{2,1,0} dot(%q, %cp.1), lhs_contracting_dims={2}, rhs_contracting_dims={2}
  %mixed = bf16[2,8,8]{2,1,0} dot(%q, %q), lhs_contracting_dims={2}, rhs_contracting_dims={2}
  %ar.3 = f32[2,8,8]{2,1,0} all-reduce(%scores), channel_id=3, replica_groups={{0,1,2,3}}, to_apply=%add
  %ag-start.4 = f32[2,8,128]{2,1,0} all-gather-start(%q), channel_id=4, replica_groups=[2,4]<=[8], dimensions={1}
  %ag-done.4 = f32[2,8,128]{2,1,0} all-gather-done(%ag-start.4)
  ROOT %out = f32[2,8,128]{2,1,0} dot(%ar.3, %cp-done.2), lhs_contracting_dims={2}, rhs_contracting_dims={1}
}
"""


def test_collective_census_counts_async_once_with_group_sizes():
    obs = hlocheck.collective_census(_HLO_RING)
    # the sync cp counts once, the -start/-done pair once more; the
    # -done halves never double-count
    assert obs["collective-permute"]["count"] == 2
    assert obs["all-reduce"]["count"] == 1
    assert obs["all-reduce"]["group_sizes"] == [4]   # {{0,1,2,3}}
    assert obs["all-gather"]["count"] == 1
    assert obs["all-gather"]["group_sizes"] == [4]   # [2,4]<=[8] iota
    assert "all-to-all" not in obs


def test_matmul_dtype_census_and_donated_params():
    assert hlocheck.matmul_dtype_census(_HLO_RING) == {"f32": 2,
                                                      "bf16": 1}
    # the alias table names params 0 and 2; 1 was dropped by XLA
    assert hlocheck.donated_param_indices(_HLO_RING) == [0, 2]
    assert hlocheck.donated_param_indices(_HLO) == []


def test_audit_flags_unexpected_collective():
    """A single-chip contract (max_total 0) catches ANY lowered
    collective — the compiler-surprise face of R11."""
    violations = hlocheck.audit_hlo(_HLO_RING,
                                    {"collectives": {"max_total": 0}},
                                    program="solo")
    assert len(violations) == 1
    v = violations[0]
    assert v["check"] == "collective-budget"
    assert v["rule"] == "replicated-psum" and v["program"] == "solo"
    assert "4 collective(s)" in v["message"]


def test_audit_per_op_min_max_bounds():
    contract = {"collectives": {
        "collective-permute": {"min": 3},   # ring didn't lower enough
        "all-reduce": {"max": 0},           # the r06 smoking gun
    }}
    msgs = [v["message"]
            for v in hlocheck.audit_hlo(_HLO_RING, contract)]
    assert len(msgs) == 2
    assert any("only 2 collective-permute(s)" in m for m in msgs)
    assert any("1 all-reduce(s)" in m for m in msgs)


def test_audit_dtype_drift():
    violations = hlocheck.audit_hlo(
        _HLO_RING, {"dtype": {"forbid": ["f32"], "allow_ops": 1}})
    assert len(violations) == 1
    assert violations[0]["rule"] == "dtype-drift"
    assert "2 f32" in violations[0]["message"]
    # within the allowance: silent
    assert hlocheck.audit_hlo(
        _HLO_RING, {"dtype": {"forbid": ["f32"], "allow_ops": 2}}) == []


def test_audit_donation_drop_is_r13s_compiled_face():
    violations = hlocheck.audit_hlo(
        _HLO_RING, {"donation": {"require_params": [0, 1, 2]}})
    assert len(violations) == 1
    assert violations[0]["rule"] == "donation-drift"
    assert "[1]" in violations[0]["message"]
    assert hlocheck.audit_hlo(
        _HLO_RING, {"donation": {"require_params": [0, 2]}}) == []


def test_audit_programs_reports_census_and_unknown_is_record_only():
    report = hlocheck.audit_programs(
        {"ring": _HLO_RING, "mystery": _HLO},
        {"programs": {"ring": {"collectives": {"all-reduce": {"max": 0}}}}})
    assert not report["ok"]
    assert [v["program"] for v in report["violations"]] == ["ring"]
    # census is recorded for every program, contracted or not
    assert report["programs"]["mystery"]["collectives"] == {}
    assert report["programs"]["ring"]["donated_params"] == [0, 2]
