"""Checkpoint conversion round-trip tests: random Flax params -> exported
HF-style torch snapshot (tests/torch_export.py, an independent inverse
mapping) -> convert.load_checkpoint -> identical tree."""

import jax
import numpy as np
import pytest

from chiaswarm_tpu.convert import load_checkpoint, merge_lora
from chiaswarm_tpu.pipelines.components import Components
from chiaswarm_tpu.pipelines.diffusion import DiffusionPipeline, GenerateRequest

from tests.torch_export import write_checkpoint


def _tree_paths(tree, prefix=""):
    out = {}
    for key, value in tree.items():
        path = f"{prefix}/{key}" if prefix else key
        if isinstance(value, dict):
            out.update(_tree_paths(value, path))
        else:
            out[path] = np.asarray(value)
    return out


@pytest.mark.parametrize("family", ["tiny", "tiny_xl", "tiny_up4"])
def test_checkpoint_roundtrip(tmp_path, family):
    src = Components.random(family, seed=7)
    write_checkpoint(tmp_path, src)
    converted = load_checkpoint(tmp_path, src.family)

    for module in src.params:
        want = _tree_paths(src.params[module])
        got = _tree_paths(converted[module])
        assert set(got) == set(want), (
            module,
            sorted(set(want) - set(got))[:5],
            sorted(set(got) - set(want))[:5],
        )
        for path, value in want.items():
            np.testing.assert_allclose(
                got[path], np.asarray(value), rtol=1e-6, atol=1e-6,
                err_msg=f"{module}/{path}",
            )


@pytest.mark.slow
def test_converted_checkpoint_generates(tmp_path):
    src = Components.random("tiny", seed=3)
    write_checkpoint(tmp_path, src)
    loaded = Components.from_checkpoint(tmp_path, "tiny", "tiny")
    pipe_src = DiffusionPipeline(src)
    pipe_new = DiffusionPipeline(loaded)
    req = GenerateRequest(prompt="same weights", steps=3, height=64,
                          width=64, seed=5, guidance_scale=4.0)
    a, _ = pipe_src(req)
    b, _ = pipe_new(req)
    np.testing.assert_array_equal(a, b)


def test_lora_merge_diffusers_format():
    src = Components.random("tiny", seed=1)
    kernel_path = ("down_0_attentions_0", "transformer_blocks_0", "attn1",
                   "to_q", "kernel")
    tree = src.params["unet"]["params"]
    orig = np.asarray(tree["down_0_attentions_0"]["transformer_blocks_0"]
                      ["attn1"]["to_q"]["kernel"])
    inner, out = orig.shape
    rank = 2
    rng = np.random.default_rng(0)
    down = rng.normal(size=(rank, inner)).astype(np.float32)
    up = rng.normal(size=(out, rank)).astype(np.float32)
    lora = {
        "down_blocks.0.attentions.0.transformer_blocks.0.attn1.processor"
        ".to_q_lora.down.weight": down,
        "down_blocks.0.attentions.0.transformer_blocks.0.attn1.processor"
        ".to_q_lora.up.weight": up,
    }
    merged, count = merge_lora(src.params["unet"], lora, scale=0.5,
                               n_levels=2)
    assert count == 1
    got = np.asarray(merged["params"]["down_0_attentions_0"]
                     ["transformer_blocks_0"]["attn1"]["to_q"]["kernel"])
    np.testing.assert_allclose(got, orig + 0.5 * (up @ down).T,
                               rtol=1e-5, atol=1e-5)


def test_lora_merge_peft_format():
    src = Components.random("tiny", seed=2)
    tree = src.params["unet"]["params"]
    orig = np.asarray(tree["mid_attention"]["transformer_blocks_0"]
                      ["attn2"]["to_v"]["kernel"])
    inner, out = orig.shape
    rng = np.random.default_rng(1)
    a = rng.normal(size=(3, inner)).astype(np.float32)
    b = rng.normal(size=(out, 3)).astype(np.float32)
    lora = {
        "unet.mid_block.attentions.0.transformer_blocks.0.attn2.to_v"
        ".lora_A.weight": a,
        "unet.mid_block.attentions.0.transformer_blocks.0.attn2.to_v"
        ".lora_B.weight": b,
    }
    merged, count = merge_lora(src.params["unet"], lora, scale=1.0,
                               n_levels=2)
    assert count == 1
    got = np.asarray(merged["params"]["mid_attention"]
                     ["transformer_blocks_0"]["attn2"]["to_v"]["kernel"])
    np.testing.assert_allclose(got, orig + (b @ a).T, rtol=1e-5, atol=1e-5)


def test_lora_incompatible_raises():
    src = Components.random("tiny", seed=4)
    with pytest.raises(ValueError, match="incompatible"):
        merge_lora(src.params["unet"],
                   {"bogus.to_q.lora_A.weight": np.zeros((2, 8), np.float32),
                    "bogus.to_q.lora_B.weight": np.zeros((8, 2), np.float32)},
                   n_levels=2)
