"""Temporal video UNet + txt2vid pipeline.

Reference behavior covered: the txt2vid workflow (swarm/video/tx2vid.py:
17-88 — 25-frame default, fps/container switch, frame-0 thumbnail),
redesigned as one jitted temporal-diffusion program.
"""

import numpy as np
import pytest

from chiaswarm_tpu.pipelines.video import (
    VIDEO_FAMILIES,
    VideoComponents,
    VideoPipeline,
    get_video_family,
)


@pytest.fixture(scope="module")
def tiny_vid():
    return VideoPipeline(VideoComponents.random("tiny_vid", seed=0))


def test_video_family_routing():
    assert get_video_family("damo-vilab/text-to-video-ms-1.7b").name == \
        "modelscope_t2v"
    assert get_video_family("random/tiny_vid").name == "tiny_vid"
    assert VIDEO_FAMILIES["modelscope_t2v"].unet.cross_attention_dim == 1024


@pytest.mark.slow
def test_inflated_temporal_layers_are_framewise_identity(tmp_path):
    """2D inflation inits the temporal modules at identity (zero conv4 /
    proj_out): identical per-frame inputs must produce identical
    per-frame outputs — the safe default for weights grafted from 2D
    checkpoints."""
    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.pipelines.components import Components
    from chiaswarm_tpu.pipelines.video import VideoComponents
    from tests.torch_export import write_checkpoint

    write_checkpoint(tmp_path, Components.random("tiny", seed=5))
    vc = VideoComponents.from_checkpoint(tmp_path, "tiny-inflated",
                                         "tiny_vid")
    frame = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 8, 8, 4))
    video = jnp.repeat(frame, 4, axis=1)   # 4 identical frames
    ctx = jax.random.normal(jax.random.PRNGKey(2),
                            (1, 77, vc.family.unet.cross_attention_dim))
    out = vc.unet.apply(vc.params["unet"], video, jnp.full((1,), 3.0), ctx)
    assert out.shape == video.shape
    for i in range(1, 4):
        np.testing.assert_allclose(np.asarray(out[:, 0]),
                                   np.asarray(out[:, i]), atol=1e-4)


@pytest.mark.slow
def test_txt2vid_pipeline(tiny_vid):
    frames, config = tiny_vid("a drifting boat", num_frames=6, steps=2,
                              seed=4, height=64, width=64)
    assert frames.shape == (6, 64, 64, 3)
    assert frames.dtype == np.uint8
    assert config["mode"] == "txt2vid"
    frames2, _ = tiny_vid("a drifting boat", num_frames=6, steps=2,
                          seed=4, height=64, width=64)
    assert np.array_equal(frames, frames2)


@pytest.mark.slow
def test_txt2vid_workload_emits_video():
    from chiaswarm_tpu.node.job_args import format_args
    from chiaswarm_tpu.node.registry import ModelRegistry

    registry = ModelRegistry(catalog=[], allow_random=True)
    job = {"workflow": "txt2vid", "model_name": "random/tiny_vid",
           "prompt": "rolling waves", "num_frames": 8,
           "num_inference_steps": 2, "height": 64, "width": 64}
    callback, kwargs = format_args(job, registry)
    artifacts, config = callback("slot0", kwargs.pop("model_name"),
                                 seed=2, **kwargs)
    assert config["mode"] == "txt2vid"
    assert config["frames"] == 8
    assert artifacts["primary"]["content_type"] == "video/mp4"
    import base64

    blob = base64.b64decode(artifacts["primary"]["blob"])
    assert len(blob) > 100  # a real container, not an empty file


@pytest.mark.slow
def test_video_inflation_matches_2d_parent_at_frame1(tmp_path):
    """2D-inflation load: spatial weights graft from an SD-style snapshot
    and the fresh temporal layers are identity, so the video UNet at F=1
    must reproduce the 2D parent UNet exactly."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chiaswarm_tpu.pipelines.components import Components
    from chiaswarm_tpu.pipelines.video import VideoComponents
    from tests.torch_export import write_checkpoint

    src = Components.random("tiny", seed=11)
    write_checkpoint(tmp_path, src)
    vc = VideoComponents.from_checkpoint(tmp_path, "tiny-inflated",
                                         "tiny_vid")

    rng = np.random.RandomState(4)
    latent = jnp.asarray(rng.randn(1, 8, 8, 4).astype(np.float32))
    t = jnp.full((1,), 400.0, jnp.float32)
    ctx = jnp.asarray(rng.randn(1, 77, 32).astype(np.float32))

    out2d = src.unet.apply(src.params["unet"], latent, t, ctx)
    out3d = vc.unet.apply(vc.params["unet"], latent[:, None], t, ctx)
    np.testing.assert_allclose(np.asarray(out3d[:, 0]), np.asarray(out2d),
                               atol=1e-5, rtol=1e-5)
    # text encoder and VAE graft byte-exactly
    a = jax.tree.leaves(src.params["text_encoder_0"])
    b = jax.tree.leaves(vc.params["text_encoder"])
    assert all(np.array_equal(x, y) for x, y in zip(a, b))


@pytest.mark.slow
def test_video_checkpoint_pipeline_generates(tmp_path):
    from chiaswarm_tpu.pipelines.components import Components
    from chiaswarm_tpu.pipelines.video import VideoComponents, VideoPipeline
    from tests.torch_export import write_checkpoint

    write_checkpoint(tmp_path, Components.random("tiny", seed=2))
    pipe = VideoPipeline(VideoComponents.from_checkpoint(
        tmp_path, "tiny-inflated", "tiny_vid"))
    frames, config = pipe("a drifting cloud", num_frames=4, steps=2,
                          height=64, width=64, seed=1)
    assert frames.shape == (4, 64, 64, 3)
    assert config["mode"] == "txt2vid"


# ---- SVD-class img2vid (BASELINE.json config #5's model class) ---------


def test_img2vid_family_routing():
    from chiaswarm_tpu.pipelines.video import get_video_family

    assert get_video_family(
        "stabilityai/stable-video-diffusion-img2vid").name == "svd_img2vid"
    assert get_video_family("random/tiny_svd").name == "tiny_svd"
    assert get_video_family("damo/text-to-video").name == "modelscope_t2v"


@pytest.mark.slow
def test_img2vid_pipeline_shapes_and_determinism():
    import numpy as np

    from chiaswarm_tpu.pipelines.video import Img2VidPipeline, VideoComponents

    c = VideoComponents.random("tiny_svd", seed=0)
    assert c.text_encoder is None and c.image_encoder is not None
    pipe = Img2VidPipeline(c)
    rng = np.random.default_rng(3)
    image = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
    frames, config = pipe(image, num_frames=4, steps=2, seed=5,
                          height=64, width=64)
    assert frames.shape == (4, 64, 64, 3) and frames.dtype == np.uint8
    assert config["mode"] == "img2vid"
    assert config["motion_bucket_id"] == 127

    again, _ = pipe(image, num_frames=4, steps=2, seed=5,
                    height=64, width=64)
    np.testing.assert_array_equal(frames, again)

    other, _ = pipe(image, num_frames=4, steps=2, seed=6,
                    height=64, width=64)
    assert not np.array_equal(frames, other)


@pytest.mark.slow
def test_img2vid_conditioning_image_matters():
    """Two different conditioning frames must produce different clips —
    the image embedding + concat latents actually steer the UNet."""
    import numpy as np

    from chiaswarm_tpu.pipelines.video import Img2VidPipeline, VideoComponents

    pipe = Img2VidPipeline(VideoComponents.random("tiny_svd", seed=1))
    rng = np.random.default_rng(0)
    img_a = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
    img_b = 255 - img_a
    a, _ = pipe(img_a, num_frames=4, steps=2, seed=9, height=64, width=64)
    b, _ = pipe(img_b, num_frames=4, steps=2, seed=9, height=64, width=64)
    assert not np.array_equal(a, b)


@pytest.mark.slow
def test_img2vid_workload_emits_video(tmp_path, monkeypatch):
    import numpy as np

    from chiaswarm_tpu.node.executor import synchronous_do_work
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.core.chip_pool import ChipPool

    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    registry = ModelRegistry(catalog=[], allow_random=True)
    pool = ChipPool(n_slots=1)
    rng = np.random.default_rng(1)
    job = {
        "id": "t-img2vid", "workflow": "img2vid",
        "model_name": "random/tiny_svd",
        "image": rng.integers(0, 255, (64, 64, 3), dtype=np.uint8),
        "num_frames": 4, "num_inference_steps": 2,
        "height": 64, "width": 64, "seed": 2,
        "content_type": "video/mp4",
    }
    result = synchronous_do_work(job, pool.slots[0], registry)
    cfg = result["pipeline_config"]
    assert "error" not in cfg, cfg
    assert cfg["mode"] == "img2vid"
    art = result["artifacts"]["primary"]
    assert art["content_type"].startswith("video/")
    assert art["blob"] and art["thumbnail"]


def test_svd_edm_schedule_tables():
    """The published SVD schedule: karras sigmas spanning (0.002, 700),
    a trailing zero, and 0.25*log(sigma) conditioning (diffusers
    EulerDiscrete timestep_type="continuous") on make_edm_schedule's own
    output (pure table math — the pipeline wiring is the slow-tier
    test below)."""
    import numpy as np

    import chiaswarm_tpu.schedulers.sampling as sampling

    sched = sampling.make_edm_schedule(0.002, 700.0, 10)
    sig = np.asarray(sched.sigmas)
    assert sig.shape == (11,) and sig[-1] == 0.0
    assert np.isclose(sig[0], 700.0, rtol=1e-4)
    assert np.isclose(sig[-2], 0.002, rtol=1e-3)
    assert (np.diff(sig) < 0).all()
    np.testing.assert_allclose(np.asarray(sched.timesteps),
                               0.25 * np.log(sig[:-1]), rtol=1e-5)


@pytest.mark.slow
def test_svd_pipeline_requests_edm_schedule(monkeypatch):
    """The img2vid pipeline actually builds its denoise on the family's
    EDM range."""
    import numpy as np

    import chiaswarm_tpu.schedulers.sampling as sampling
    from chiaswarm_tpu.pipelines.video import Img2VidPipeline, VideoComponents

    import chiaswarm_tpu.pipelines.video as video_mod

    pipe = Img2VidPipeline(VideoComponents.random("tiny_svd", seed=0))
    calls = []
    orig = sampling.make_edm_schedule

    def spy(smin, smax, n):
        calls.append((smin, smax, n))
        return orig(smin, smax, n)

    monkeypatch.setattr(video_mod, "make_edm_schedule", spy)
    rng = np.random.default_rng(1)
    frames, cfg = pipe(rng.integers(0, 255, (64, 64, 3), dtype=np.uint8),
                       num_frames=4, steps=2, height=64, width=64, seed=1)
    assert frames.shape == (4, 64, 64, 3)
    assert calls == [pipe.c.family.edm_sigma_range + (2,)]
