"""Spec consumers: one clean, one drifted axis, one arity mismatch, one
unbound collective parameter — each invisible to any per-file pass."""

from jax.sharding import PartitionSpec as P

from driftpkg.kernels import orphan_axis, ring
from driftpkg.mesh import DATA_AXIS


def clean_spec():
    return P(DATA_AXIS, None)


def drifted_spec():
    return P("batch", None)  # no mesh anywhere binds "batch"


def wrong_arity(mesh, q, k):
    from chiaswarm_tpu.core.compat import shard_map

    spec = P(DATA_AXIS)
    # ring() takes THREE positional args; in_specs supplies two
    fn = shard_map(ring, mesh=mesh, in_specs=(spec, spec), out_specs=spec)
    return fn(q, k)


def forgets_the_axis(x):
    return orphan_axis(x)  # TypeError at run time: axis_name unbound
