"""Collective kernels consumed (and mis-consumed) across the package."""

import jax


def ring(q, k, v, *, axis_name):
    return jax.lax.ppermute(q, axis_name, [(0, 1)])


def orphan_axis(x, *, axis_name):
    # axis_name reaches a collective but specs.py's caller never binds it
    return jax.lax.psum(x, axis_name)
