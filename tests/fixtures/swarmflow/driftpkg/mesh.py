"""The mesh vocabulary: exactly two axis names exist."""

DATA_AXIS = "data"
MODEL_AXIS = "model"
DEFAULT_AXES = (DATA_AXIS, MODEL_AXIS)
