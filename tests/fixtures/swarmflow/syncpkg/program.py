"""The compiled side: jitted entry whose helper lives one module away."""

import jax

from syncpkg.helpers import postprocess_mean


@jax.jit
def step(x):
    # looks pure from THIS file — the sync is in helpers.py
    return postprocess_mean(x) + 1.0
