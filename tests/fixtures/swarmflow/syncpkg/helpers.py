"""The host side: fine on its own, fatal when reached from jit."""


def harmless(x):
    return x * 2


def postprocess_mean(x):
    return x.mean().item()
