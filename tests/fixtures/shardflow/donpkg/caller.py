"""R13 donation-drift: reading a buffer after donating it to a jitted
wrapper defined in another module, next to the clean rebinding twin."""

from donpkg.wrappers import step


def bad_read_after_donate(latents, eps):
    out = step(latents, eps)
    # latents was donated at the call above: XLA has reused its memory
    return out + latents.mean()


def clean_rebound(latents, eps):
    latents = step(latents, eps)
    return latents * 2.0
