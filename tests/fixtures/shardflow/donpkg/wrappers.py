"""The jitted wrapper lives HERE; the use-after-donate lives one module
away — the cross-module case a per-file pass cannot see."""

import jax


def _denoise_step(latents, eps):
    return latents - 0.1 * eps


step = jax.jit(_denoise_step, donate_argnums=(0,))
