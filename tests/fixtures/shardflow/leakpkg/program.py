"""R12 unreduced-out-spec: a per-shard partial sum escapes a shard_map
boundary whose out_specs claims it is replicated, next to the clean
twin that reduces before returning."""

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from chiaswarm_tpu.core.compat import shard_map

MESH = Mesh(np.array(jax.devices()[:4]), ("seq",))


def partial_logits(x):
    # per-shard partial reduction: still varies over seq
    return x.sum(axis=-1)


def reduced_logits(x):
    return jax.lax.psum(x.sum(axis=-1), "seq")


def bad_escape(x):
    # out_specs P() claims the result is replicated over seq, but each
    # shard returns ITS partial sum — callers read shard-0's garbage.
    fn = shard_map(partial_logits, mesh=MESH, in_specs=(P("seq"),),
                   out_specs=P())
    return fn(x)


def clean_reduced(x):
    # the psum clears seq from the varying set: P() is now honest.
    fn = shard_map(reduced_logits, mesh=MESH, in_specs=(P("seq"),),
                   out_specs=P())
    return fn(x)
