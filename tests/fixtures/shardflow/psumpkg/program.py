"""Three bindings of the same kernel. Only the two-axis one is wrong:
the replicated ctx makes the product complete on every seq shard, so
the kernel's psum multiplies K/V by exactly the seq size."""

from functools import partial

from jax.sharding import PartitionSpec as P

from chiaswarm_tpu.core.compat import shard_map
from psumpkg.kernels import kv_projection
from psumpkg.mesh import RING, SEQ_ONLY


def bad_two_axis(ctx, w):
    # ctx is sharded over data ONLY: replicated over seq. The product
    # is already complete on every seq shard — the kernel's psum over
    # seq multiplies it by 4 (R11 replicated-psum).
    fn = shard_map(partial(kv_projection, axis_name="seq"), mesh=RING,
                   in_specs=(P("data", None), P()),
                   out_specs=P("data", None))
    return fn(ctx, w)


def clean_single_axis(ctx, w):
    # same mesh, single sharded axis: ctx varies over seq, so the psum
    # is a genuine reduction of per-shard partials.
    fn = shard_map(partial(kv_projection, axis_name="seq"), mesh=RING,
                   in_specs=(P(None, "seq"), P()),
                   out_specs=P(None, None))
    return fn(ctx, w)


def clean_pure_seq_mesh(ctx, w):
    # the pure-seq twin (bit-identical in the r06 bisect): one mesh
    # axis, varying operand, legitimate psum.
    fn = shard_map(partial(kv_projection, axis_name="seq"),
                   mesh=SEQ_ONLY, in_specs=(P("seq"), P()),
                   out_specs=P())
    return fn(ctx, w)
