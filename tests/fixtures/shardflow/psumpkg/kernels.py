"""The K/V projection shape from parallel/ring_attention.py's entry:
project, then all-reduce the product over the ring axis. Whether that
psum is a reduction or a multiplication depends entirely on what the
shard_map boundary fed in — which is the r06 bug class."""

import jax


def kv_projection(ctx, w, *, axis_name):
    kv = ctx @ w
    return jax.lax.psum(kv, axis_name)
