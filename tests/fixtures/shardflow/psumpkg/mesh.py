"""Two distinct mesh instances: the divergence-family trigger shape
(two-axis) and the pure-seq twin that has always been bit-exact."""

import jax
import numpy as np
from jax.sharding import Mesh

RING = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "seq"))
SEQ_ONLY = Mesh(np.array(jax.devices()[:4]), ("seq",))
