import jax
import numpy as np
from jax.sharding import Mesh

RING = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "seq"))
