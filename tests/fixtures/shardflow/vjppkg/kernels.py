"""A custom_vjp matmul whose backward pass all-reduces the weight
gradient over ``seq``. No user code ever calls ``matmul_bwd`` — jax
dispatches it inside the same shard_map context as the primal — so
whether that psum is a reduction or a multiplication is decided
entirely by the primal's in_specs."""

import jax


@jax.custom_vjp
def matmul(ctx, w):
    return ctx @ w


def matmul_fwd(ctx, w):
    return ctx @ w, (ctx, w)


def matmul_bwd(res, g):
    ctx, w = res
    dw = jax.lax.psum(ctx.T @ g, "seq")
    return g @ w.T, dw


matmul.defvjp(matmul_fwd, matmul_bwd)
