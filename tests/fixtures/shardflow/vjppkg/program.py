"""Two bindings of the custom_vjp kernel. The data-only one is wrong:
ctx is replicated over seq, so the residuals the bwd psums over seq
are already complete on every seq shard — the gradient comes back
multiplied by the seq size, through a call edge no call graph sees."""

from jax.sharding import PartitionSpec as P

from chiaswarm_tpu.core.compat import shard_map
from vjppkg.kernels import matmul
from vjppkg.mesh import RING


def bad_replicated_grad(ctx, w):
    # ctx sharded over data ONLY: replicated over seq. The bwd body's
    # psum over seq multiplies dw by 4 (R11 via the defvjp edge).
    fn = shard_map(matmul, mesh=RING,
                   in_specs=(P("data", None), P()),
                   out_specs=P("data", None))
    return fn(ctx, w)


def clean_seq_varying(ctx, w):
    # ctx varies over seq: the bwd psum is a genuine reduction of
    # per-shard partial gradients, and the (still seq-varying) primal
    # output leaves labeled seq-sharded.
    fn = shard_map(matmul, mesh=RING,
                   in_specs=(P(None, "seq"), P()),
                   out_specs=P(None, "seq"))
    return fn(ctx, w)
