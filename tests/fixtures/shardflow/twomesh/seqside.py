"""An unrelated seq-parallel corner of the project: its mesh's axis
vocabulary must stay ITS OWN."""

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from chiaswarm_tpu.core.compat import shard_map

SEQ_MESH = Mesh(np.array(jax.devices()[:4]), ("seq",))


def shard_over_seq(x):
    # legitimate: this site's mesh binds seq
    fn = shard_map(lambda a: a, mesh=SEQ_MESH, in_specs=(P("seq"),),
                   out_specs=P("seq"))
    return fn(x)
