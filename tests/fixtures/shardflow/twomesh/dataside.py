"""The retired R10 imprecision: before per-mesh-instance universes, the
'seq' defined by seqside.py's mesh pooled into one global soup and
sanctioned this spec — which names an axis THIS mesh does not bind."""

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from chiaswarm_tpu.core.compat import shard_map

DATA_MESH = Mesh(np.array(jax.devices()[:2]), ("data",))


def shard_over_wrong_axis(x):
    # 'seq' exists in the project (seqside.SEQ_MESH) but not on
    # DATA_MESH — jax raises at trace time; R10 must catch it statically
    fn = shard_map(lambda a: a, mesh=DATA_MESH, in_specs=(P("seq"),),
                   out_specs=P("seq"))
    return fn(x)


def shard_over_bound_axis(x):
    fn = shard_map(lambda a: a, mesh=DATA_MESH, in_specs=(P("data"),),
                   out_specs=P("data"))
    return fn(x)
