"""R20: id()/repr() flow into the PERSISTENT key surface — stable
within one process, different in the next, so a shipped artifact keyed
by them can never hit."""

from unstablepkg.cache import artifact_cache_key


def ship(model, tag):
    return artifact_cache_key(tag, (id(model), repr(model.cfg)))
