"""Clean twin: the persistent key carries stable content — and the
IN-PROCESS key keeps its id()-based owner, proving the two surfaces are
judged differently (id(self._c) is the point of having both)."""

from unstablepkg.cache import artifact_cache_key, static_cache_key


class Engine:
    def __init__(self, components):
        self._c = components

    def key(self, tag):
        return static_cache_key(id(self._c), tag, {"b": 1})


def ship(model, tag):
    return artifact_cache_key(tag, (model.name, str(model.dtype)))
