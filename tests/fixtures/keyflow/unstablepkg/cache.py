"""Local key builders for both surfaces: the in-process key (id()-based
owners welcome) and the persistent artifact key (everything must be
stable across processes)."""


def static_cache_key(owner, tag, static):
    return (owner, tag, tuple(sorted(static.items())))


def artifact_cache_key(tag, parts):
    return ("exec-v1", tag) + tuple(parts)
