"""Clean twin: each program gets its own tag — two slots, no aliasing."""

import jax

from collidepkg.cache import static_cache_key


class Engine:
    def __init__(self, cache, components):
        self._cache = cache
        self._c = components

    def encode(self, x):
        key = static_cache_key(id(self._c), "encode", {"h": 64})
        return self._cache.get_or_create(
            key, lambda: jax.jit(lambda v: v * 2.0))(x)

    def decode(self, x):
        key = static_cache_key(id(self._c), "decode", {"h": 64})
        return self._cache.get_or_create(
            key, lambda: jax.jit(lambda v: v + 1.0))(x)
