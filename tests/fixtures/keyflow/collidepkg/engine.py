"""R21: encode and decode build DIFFERENT programs under the SAME
(owner, tag, statics) vocabulary — both land in one executable slot and
whichever builds second silently serves the first's program."""

import jax

from collidepkg.cache import static_cache_key


class Engine:
    def __init__(self, cache, components):
        self._cache = cache
        self._c = components

    def encode(self, x):
        key = static_cache_key(id(self._c), "run", {"h": 64})
        return self._cache.get_or_create(
            key, lambda: jax.jit(lambda v: v * 2.0))(x)

    def decode(self, x):
        key = static_cache_key(id(self._c), "run", {"h": 64})
        return self._cache.get_or_create(
            key, lambda: jax.jit(lambda v: v + 1.0))(x)
