"""Local key builder; the collision is in the callers' vocabulary, not
in the builder itself."""


def static_cache_key(owner, tag, static):
    return (owner, tag, tuple(sorted(static.items())))
