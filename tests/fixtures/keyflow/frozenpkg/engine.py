"""R19 both scopes: an env read inside a jitted body, and one inside a
factory closure handed to get_or_create — each looks live-per-call but
executes at most once per cache slot, so a warm hit freezes it."""

import os

import jax

from frozenpkg.cache import static_cache_key


class Slots:
    def __init__(self):
        self._e = {}

    def get_or_create(self, key, factory):
        if key not in self._e:
            self._e[key] = factory()
        return self._e[key]


@jax.jit
def step(x):
    scale = float(os.environ.get("FIXTURE_SCALE", "1.0"))
    return x * scale


def _build():
    mode = os.environ.get("FIXTURE_MODE", "fast")
    return jax.jit(lambda x: x * (2.0 if mode == "fast" else 3.0))


def get(slots, owner):
    key = static_cache_key(owner, "step", {"b": 1})
    return slots.get_or_create(key, _build)
