"""Clean twin: the env is read at DISPATCH and handed to the traced
body as an argument — every call sees the live value, and the argument
participates in jit's own argument keying."""

import os

import jax


@jax.jit
def _step(x, scale):
    return x * scale


def run(x):
    scale = float(os.environ.get("FIXTURE_SCALE", "1.0"))
    return _step(x, scale)
