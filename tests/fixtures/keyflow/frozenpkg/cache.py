"""Local key builder; the fixture's keyed vocabulary is empty on
purpose — R19 is about WHERE the read happens, not what the key holds,
and an unkeyed read inside a build scope is the live-looking-but-frozen
shape."""


def static_cache_key(owner, tag, static):
    return (owner, tag, tuple(sorted(static.items())))
