"""Clean twin: the same two shapes, but every knob the trace consumes
is in the key builder's _TRACE_KNOBS vocabulary — a flip retraces."""

import os

import jax
import jax.numpy as jnp

from unkeyedpkg.cache import static_cache_key

_CLEAN_BLOCK = int(os.environ.get("FIXTURE_CLEAN_BLOCK", "128"))


def _impl():
    return os.environ.get("FIXTURE_CLEAN_IMPL", "einsum")


def _fwd(x):
    if _impl() == "flash":
        return x * 2.0
    return x * jnp.float32(_CLEAN_BLOCK)


def build(cache, owner):
    key = static_cache_key(owner, "fwd", {"b": 1})
    return cache.get_or_create(key, lambda: jax.jit(_fwd))
