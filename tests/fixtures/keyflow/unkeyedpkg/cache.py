"""Local key builder: keyflow matches builders by name, so this
package-scoped static_cache_key defines the fixture's keyed vocabulary
(_TRACE_KNOBS) without importing the real core/compile_cache.py."""

_TRACE_KNOBS = ("FIXTURE_CLEAN_IMPL", "FIXTURE_CLEAN_BLOCK")


def _knobs():
    import os

    return tuple((n, os.environ[n]) for n in _TRACE_KNOBS
                 if os.environ.get(n))


def static_cache_key(owner, tag, static):
    key = (owner, tag, tuple(sorted(static.items())))
    knobs = _knobs()
    if knobs:
        key = key + (("knobs", knobs),)
    return key
