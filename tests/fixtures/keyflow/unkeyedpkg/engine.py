"""Both R18 faces: a trace-time env read the key never learns about
(the CHIASWARM_ATTENTION shape), and an import-time read frozen into a
module constant the traced body loads (the flash-block shape)."""

import os

import jax
import jax.numpy as jnp

from unkeyedpkg.cache import static_cache_key

_BLOCK = int(os.environ.get("FIXTURE_BLOCK", "128"))


def _impl():
    return os.environ.get("FIXTURE_IMPL", "einsum")


def _fwd(x):
    if _impl() == "flash":
        return x * 2.0
    return x * jnp.float32(_BLOCK)


def build(cache, owner):
    key = static_cache_key(owner, "fwd", {"b": 1})
    return cache.get_or_create(key, lambda: jax.jit(_fwd))
