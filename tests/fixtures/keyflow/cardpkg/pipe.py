"""R6's interprocedural face: the raw request attribute and the key
site live in DIFFERENT functions, so the per-function pass cannot see
that every distinct req.height mints a fresh executable slot (and a
fresh XLA compile). Plus the display shape: a list of varying values
inside the static dict is an unbounded-cardinality key component."""

from cardpkg.cache import static_cache_key


def _get_fn(cache, h):
    key = static_cache_key(0, "gen", {"h": h})
    return cache.get_or_create(key, lambda: object())


def handle(cache, req):
    return _get_fn(cache, req.height)


def _get_fn_sizes(cache, h, w):
    key = static_cache_key(0, "gen2", {"sizes": [h, w]})
    return cache.get_or_create(key, lambda: object())
