"""Local key builder plus the bucketing helper the clean twin uses."""


def static_cache_key(owner, tag, static):
    return (owner, tag, tuple(sorted(static.items())))


def bucket_batch(n):
    p = 1
    while p < n:
        p *= 2
    return p
