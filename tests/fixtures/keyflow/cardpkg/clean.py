"""Clean twin: the caller snaps the raw attribute onto the bucket
lattice before it reaches the key-site parameter — bounded slots."""

from cardpkg.cache import bucket_batch, static_cache_key


def _get_fn(cache, h):
    key = static_cache_key(0, "gen_clean", {"h": h})
    return cache.get_or_create(key, lambda: object())


def handle(cache, req):
    return _get_fn(cache, bucket_batch(req.height))
