"""PR-3's first container hazard: jit dispatch is async, so the value a
worker thread drops into a shared container may still be in flight when
another root picks it up — the result materializes later, on a thread
the consumer never synchronized with."""

import collections
import threading

import jax


class Lane:
    def __init__(self):
        self._out = collections.deque()
        self._step = jax.jit(lambda x: x * 2)
        threading.Thread(target=self._drive, daemon=True).start()

    def _drive(self):
        y = self._step(1.0)
        self._out.append(y)  # R14: device value published cross-thread

    async def poll(self):
        if self._out:
            return self._out.popleft()
        return None
