"""Clean twin: the handoff is committed before it is published —
block_until_ready pins the value to a completed buffer."""

import collections
import threading

import jax


class SyncLane:
    def __init__(self):
        self._out = collections.deque()
        self._step = jax.jit(lambda x: x * 2)
        threading.Thread(target=self._drive, daemon=True).start()

    def _drive(self):
        y = jax.block_until_ready(self._step(1.0))
        self._out.append(y)

    async def poll(self):
        if self._out:
            return self._out.popleft()
        return None
