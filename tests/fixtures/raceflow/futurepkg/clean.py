"""Clean twin: ``.copy()`` forces completion and hands the consumer a
committed host-side buffer."""

import asyncio

import jax


@jax.jit
def _decode(x):
    return x + 1


class SafePool:
    def __init__(self):
        self._results = {}

    async def submit(self, key, x):
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._job, key, x)

    def _job(self, key, x):
        self._results[key] = _decode(x).copy()

    async def poll(self, key):
        return self._results.pop(key, None)
