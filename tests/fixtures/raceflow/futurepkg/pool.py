"""PR-3's second container hazard: an executor job parks a jit result
in a request-keyed dict; the async poller that pops it runs on the
event loop, which never synchronized with the dispatching thread."""

import asyncio

import jax


@jax.jit
def _decode(x):
    return x + 1


class Pool:
    def __init__(self):
        self._results = {}

    async def submit(self, key, x):
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._job, key, x)

    def _job(self, key, x):
        self._results[key] = _decode(x)  # R14: in-flight value shared

    async def poll(self, key):
        return self._results.pop(key, None)
