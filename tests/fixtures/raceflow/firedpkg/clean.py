"""Clean twin: both writers take the Condition."""

import threading


class SafeWatch:
    def __init__(self):
        self._cv = threading.Condition()
        self.fired = False
        threading.Thread(target=self._monitor, daemon=True).start()
        threading.Thread(target=self._reset_loop, daemon=True).start()

    def _monitor(self):
        with self._cv:
            self.fired = True
            self._cv.notify_all()

    def _reset_loop(self):
        with self._cv:
            self.fired = False
