"""PR-10's fired-vs-condemn shape: ``fired`` is Condition-guarded on
the monitor path, but the reset path writes it bare — the exact
mostly-locked discipline break RacerD keys on."""

import threading


class Watch:
    def __init__(self):
        self._cv = threading.Condition()
        self.fired = False
        threading.Thread(target=self._monitor, daemon=True).start()
        threading.Thread(target=self._reset_loop, daemon=True).start()

    def _monitor(self):
        with self._cv:
            self.fired = True
            self._cv.notify_all()

    def _reset_loop(self):
        self.fired = False  # R15: unguarded write to guarded state
