"""Clean twin: both threads honor the same C -> D order (its own lock
pair — sharing A/B with workers.py would pair with *that* module's
inverted edge, which is exactly what R16 is for)."""

import threading

C = threading.Lock()
D = threading.Lock()


def first():
    with C:
        with D:
            pass


def second():
    with C:
        with D:
            pass


threading.Thread(target=first, daemon=True).start()
threading.Thread(target=second, daemon=True).start()
