"""Textbook ABBA: two threads acquire the same two module locks in
opposite order — each can hold one and wait forever on the other."""

import threading

from abbapkg.locks import A, B


def forward():
    with A:
        with B:  # R16: A -> B here ...
            pass


def backward():
    with B:
        with A:  # ... B -> A on the other thread
            pass


threading.Thread(target=forward, daemon=True).start()
threading.Thread(target=backward, daemon=True).start()
