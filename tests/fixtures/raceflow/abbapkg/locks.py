import threading

A = threading.Lock()
B = threading.Lock()
