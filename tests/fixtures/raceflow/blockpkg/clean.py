"""Clean twin: an asyncio.Lock may span awaits (it suspends, not
blocks), and sleeping means awaiting asyncio.sleep."""

import asyncio

ALOCK = asyncio.Lock()


async def tick():
    async with ALOCK:
        await asyncio.sleep(0.1)


async def nap():
    await asyncio.sleep(1.0)
