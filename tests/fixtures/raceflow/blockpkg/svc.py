"""Both R17 shapes: a threading (not asyncio) lock held across an
``await`` blocks every other task that wants the lock for the whole
suspension; ``time.sleep`` in a coroutine freezes the entire loop."""

import asyncio
import threading
import time

LOCK = threading.Lock()


async def tick():
    with LOCK:
        await asyncio.sleep(0.1)  # R17: threading lock across await


async def nap():
    time.sleep(1.0)  # R17: blocking call on the event loop
