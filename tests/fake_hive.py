"""FakeHive: an in-process hive server for hermetic worker tests.

Serves the three endpoints of the hive protocol (swarm/worker.py:66-78,
150-158; swarm/initialize.py:101-107) plus static test assets (input
images), so the whole poll -> execute -> upload loop runs with zero
network. This is the testability gap SURVEY.md §4 commits to fixing.
"""

from __future__ import annotations

import asyncio
import io
from typing import Any

from aiohttp import web


class FakeHive:
    def __init__(self) -> None:
        self.jobs: list[dict[str, Any]] = []
        self.results: list[dict[str, Any]] = []
        self.models: list[dict[str, Any]] = []
        self.result_event = asyncio.Event()
        self._app = web.Application(client_max_size=256 * 1024 * 1024)
        self._app.router.add_get("/api/work", self._work)
        self._app.router.add_post("/api/results", self._results)
        self._app.router.add_get("/api/models", self._models)
        self._app.router.add_route("*", "/assets/image.png", self._image)
        self._runner: web.AppRunner | None = None
        self.uri = ""

    # ---- endpoints ----

    async def _work(self, request: web.Request) -> web.Response:
        jobs, self.jobs = self.jobs, []
        return web.json_response({"jobs": jobs})

    async def _results(self, request: web.Request) -> web.Response:
        self.results.append(await request.json())
        self.result_event.set()
        return web.json_response({"status": "ok"})

    async def _models(self, request: web.Request) -> web.Response:
        return web.json_response({"models": self.models})

    async def _image(self, request: web.Request) -> web.Response:
        from PIL import Image

        buf = io.BytesIO()
        Image.new("RGB", (96, 96), (200, 120, 40)).save(buf, format="PNG")
        return web.Response(body=buf.getvalue(), content_type="image/png")

    # ---- lifecycle ----

    async def start(self) -> str:
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.uri = f"http://127.0.0.1:{port}"
        return self.uri

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def wait_for_results(self, n: int, timeout: float = 120.0) -> None:
        async def _wait():
            while len(self.results) < n:
                self.result_event.clear()
                await self.result_event.wait()

        await asyncio.wait_for(_wait(), timeout)
