"""LoRA serving path: job-carried adapters reach the pipeline cache.

Reference behavior covered: per-job ``lora`` + ``cross_attention_scale``
(swarm/diffusion/diffusion_func.py:20-22,58-68 — ``unet.load_attn_procs``
plus runtime ``cross_attention_kwargs={"scale": s}``). Here the scaled
deltas merge into a separately-LRU-keyed param tree at load time
(node/registry.py), so a job with ``lora`` must produce a different image
than the same job without, while the base entry stays pristine.
"""

import numpy as np
import pytest

from chiaswarm_tpu.core.chip_pool import ChipPool
from chiaswarm_tpu.node.executor import synchronous_do_work
from chiaswarm_tpu.node.registry import ModelRegistry, model_dir
from chiaswarm_tpu.pipelines import Components


@pytest.fixture()
def registry():
    return ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True,
    )


@pytest.fixture()
def pool():
    return ChipPool(n_slots=1)


def _write_tiny_lora(name: str, scale_mag: float = 1.0) -> None:
    """Write a rank-2 adapter (diffusers attn-procs layout) matching the
    tiny family's down_0 attn1.to_q projection into model_dir(name)."""
    from safetensors.numpy import save_file

    c = Components.random("tiny", seed=0)
    kernel = np.asarray(c.params["unet"]["params"]["down_0_attentions_0"]
                        ["transformer_blocks_0"]["attn1"]["to_q"]["kernel"])
    inner, out = kernel.shape
    rng = np.random.default_rng(7)
    down = (scale_mag * rng.normal(size=(2, inner))).astype(np.float32)
    up = (scale_mag * rng.normal(size=(out, 2))).astype(np.float32)
    d = model_dir(name)
    d.mkdir(parents=True, exist_ok=True)
    save_file(
        {
            "down_blocks.0.attentions.0.transformer_blocks.0.attn1"
            ".processor.to_q_lora.down.weight": down,
            "down_blocks.0.attentions.0.transformer_blocks.0.attn1"
            ".processor.to_q_lora.up.weight": up,
        },
        str(d / "adapter.safetensors"),
    )


@pytest.mark.slow
def test_job_with_lora_changes_output(tmp_path, monkeypatch, registry, pool):
    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    _write_tiny_lora("acme/style-lora")

    base_job = {"id": "j-base", "model_name": "tiny", "prompt": "a fish",
                "seed": 11,
                "num_inference_steps": 2, "height": 64, "width": 64}
    lora_job = dict(base_job, id="j-lora", lora="acme/style-lora",
                    cross_attention_scale=0.8)

    base = synchronous_do_work(base_job, pool.slots[0], registry)
    with_lora = synchronous_do_work(lora_job, pool.slots[0], registry)

    assert "fatal_error" not in base and "fatal_error" not in with_lora
    assert with_lora["pipeline_config"]["lora"] == "acme/style-lora"
    assert with_lora["pipeline_config"]["cross_attention_scale"] == 0.8
    assert (base["artifacts"]["primary"]["blob"]
            != with_lora["artifacts"]["primary"]["blob"])

    # base entry unchanged by the merge: re-running the plain job
    # reproduces the original bytes
    again = synchronous_do_work(dict(base_job, id="j-base2"), pool.slots[0],
                                registry)
    assert (again["artifacts"]["primary"]["blob"]
            == base["artifacts"]["primary"]["blob"])


@pytest.mark.slow
def test_lora_entries_are_cache_keyed_by_scale(tmp_path, monkeypatch,
                                               registry):
    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    _write_tiny_lora("acme/style-lora")

    plain = registry.pipeline("tiny")
    merged_a = registry.pipeline("tiny", lora="acme/style-lora",
                                 lora_scale=1.0)
    merged_b = registry.pipeline("tiny", lora="acme/style-lora",
                                 lora_scale=0.25)
    assert plain is not merged_a and merged_a is not merged_b
    # same (lora, scale) -> same resident entry
    assert registry.pipeline("tiny", lora="acme/style-lora",
                             lora_scale=1.0) is merged_a

    k_plain = np.asarray(plain.c.params["unet"]["params"]
                         ["down_0_attentions_0"]["transformer_blocks_0"]
                         ["attn1"]["to_q"]["kernel"])
    k_a = np.asarray(merged_a.c.params["unet"]["params"]
                     ["down_0_attentions_0"]["transformer_blocks_0"]
                     ["attn1"]["to_q"]["kernel"])
    k_b = np.asarray(merged_b.c.params["unet"]["params"]
                     ["down_0_attentions_0"]["transformer_blocks_0"]
                     ["attn1"]["to_q"]["kernel"])
    assert not np.array_equal(k_plain, k_a)
    # scale 0.25 delta == 1/4 of scale 1.0 delta
    np.testing.assert_allclose(k_b - k_plain, (k_a - k_plain) / 4.0,
                               rtol=1e-4, atol=1e-6)


def test_missing_lora_is_redispatchable(tmp_path, monkeypatch, registry,
                                        pool):
    """ISSUE 6 taxonomy resolution: a LoRA missing from THIS node is the
    same node-local availability problem as a missing checkpoint — the
    envelope uploads as ``error_kind=model_unavailable`` WITHOUT the
    fatal flag, so a lease-aware hive (node/minihive.py) redispatches it
    to a node that downloaded the adapter (bounded by max_attempts)."""
    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    job = {"id": "j-miss", "model_name": "tiny", "prompt": "x",
           "num_inference_steps": 1, "height": 64, "width": 64,
           "lora": "acme/not-downloaded"}
    result = synchronous_do_work(job, pool.slots[0], registry)
    assert "fatal_error" not in result
    config = result["pipeline_config"]
    assert config["error_kind"] == "model_unavailable"
    assert "not available" in config["error"]
