"""CLI-layer tests: smoke harness and init catalog fetch against FakeHive."""

import asyncio
import json
import os

import pytest

from chiaswarm_tpu.node.smoke import SMOKE_JOBS, run_smoke

from tests.fake_hive import FakeHive


@pytest.mark.slow
def test_smoke_txt2img_ok():
    result = run_smoke("txt2img")
    assert "error" not in result["pipeline_config"]
    assert "primary" in result["artifacts"]


@pytest.mark.slow
def test_smoke_img2img_ok():
    result = run_smoke("img2img")
    assert "error" not in result["pipeline_config"]
    assert result["pipeline_config"]["mode"] == "img2img"


@pytest.mark.slow
def test_smoke_txt2audio_and_cascade_ok():
    """Formerly fatal stubs — now real jitted pipelines."""
    result = run_smoke("txt2audio")
    assert "fatal_error" not in result
    assert result["artifacts"]["primary"]["content_type"] in (
        "audio/wav", "audio/mpeg")  # mpeg when an ffmpeg binary is present
    result = run_smoke("cascade")
    assert "fatal_error" not in result
    assert result["pipeline_config"]["mode"] == "cascade_txt2img"


@pytest.mark.slow
def test_smoke_txt2vid_ok():
    result = run_smoke("txt2vid")
    assert "fatal_error" not in result
    assert result["pipeline_config"]["mode"] == "txt2vid"
    assert result["artifacts"]["primary"]["content_type"] == "video/mp4"
    assert "thumbnail" in result["artifacts"]


def test_smoke_covers_every_routed_workflow():
    # the smoke matrix must keep pace with the dispatcher's routing table
    assert {"txt2img", "img2img", "txt2audio", "txt2vid", "img2txt",
            "cascade"} <= set(SMOKE_JOBS)


def test_init_fetches_catalog(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))

    async def scenario():
        hive = FakeHive()
        uri = await hive.start()
        hive.models = [{"name": "tiny", "family": "tiny",
                        "parameters": {"can_preload": False}}]
        monkeypatch.setenv("SDAAS_URI", uri)
        monkeypatch.setenv("SDAAS_TOKEN", "token")
        from chiaswarm_tpu.node.initialize import init

        code = await init(["--silent", "--no-prefetch"])
        await hive.stop()
        return code

    assert asyncio.run(scenario()) == 0
    catalog = json.loads((tmp_path / "models.json").read_text())
    assert catalog[0]["name"] == "tiny"
    settings = json.loads((tmp_path / "settings.json").read_text())
    assert settings["hive_token"] == "token"


def test_annotators_cover_every_learned_mode():
    """A fresh `swarm-tpu init` must provision weights for ALL six
    learned preprocessor networks — a mode with a native model but no
    provisioned weights would silently serve its stand-in forever."""
    from chiaswarm_tpu.node.initialize import _ANNOTATORS

    assert {"openpose", "hed", "dpt", "upernet", "mlsd",
            "lineart"} <= set(_ANNOTATORS)
    hinted = {h for hints, _, _ in _ANNOTATORS.values() for h in hints}
    assert {"mlsd", "lineart"} <= hinted


def test_sd_generation_model_detection():
    from chiaswarm_tpu.node.initialize import _is_sd_generation_model

    assert _is_sd_generation_model({"name": "runwayml/stable-diffusion-v1-5"})
    assert _is_sd_generation_model({"name": "DeepFloyd/IF-I-XL-v1.0"})
    assert not _is_sd_generation_model({"name": "cvssp/audioldm-s-full-v2"})
    assert not _is_sd_generation_model({"name": "suno/bark"})
    assert not _is_sd_generation_model(
        {"name": "Salesforce/blip-image-captioning-large"})
    assert not _is_sd_generation_model(
        {"name": "damo/text-to-video",
         "parameters": {"workflow": "txt2vid"}})
    assert not _is_sd_generation_model({})


def test_init_provisions_safety_checker(tmp_path, monkeypatch):
    """When the catalog lists an SD model, prefetch provisions the
    standalone safety checker into the model store (fake hub module —
    zero-egress hosts skip with a warning instead)."""
    import sys
    import types

    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))

    def fake_snapshot_download(repo, local_dir=None, **kwargs):
        from pathlib import Path

        Path(local_dir).mkdir(parents=True, exist_ok=True)
        (Path(local_dir) / "model.safetensors").write_bytes(b"")

    fake_hub = types.ModuleType("huggingface_hub")
    fake_hub.snapshot_download = fake_snapshot_download
    monkeypatch.setitem(sys.modules, "huggingface_hub", fake_hub)

    from chiaswarm_tpu.node.initialize import (
        _prefetch_safety_checker,
    )
    from chiaswarm_tpu.node.registry import model_dir
    from chiaswarm_tpu.node.settings import Settings

    models = [{"name": "runwayml/stable-diffusion-v1-5",
               "parameters": {}}]
    assert _prefetch_safety_checker(models, Settings()) == 1
    target = model_dir("CompVis/stable-diffusion-safety-checker")
    assert (target / "model.safetensors").exists()
    # idempotent: an existing dir is never re-fetched
    assert _prefetch_safety_checker(models, Settings()) == 0
    # audio-only catalogs provision nothing (fresh root)
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path / "audio-only"))
    assert _prefetch_safety_checker(
        [{"name": "cvssp/audioldm-s-full-v2"}], Settings()) == 0
