"""CLI-layer tests: smoke harness and init catalog fetch against FakeHive."""

import asyncio
import json
import os

from chiaswarm_tpu.node.smoke import SMOKE_JOBS, run_smoke

from tests.fake_hive import FakeHive


def test_smoke_txt2img_ok():
    result = run_smoke("txt2img")
    assert "error" not in result["pipeline_config"]
    assert "primary" in result["artifacts"]


def test_smoke_img2img_ok():
    result = run_smoke("img2img")
    assert "error" not in result["pipeline_config"]
    assert result["pipeline_config"]["mode"] == "img2img"


def test_smoke_txt2audio_and_cascade_ok():
    """Formerly fatal stubs — now real jitted pipelines."""
    result = run_smoke("txt2audio")
    assert "fatal_error" not in result
    assert result["artifacts"]["primary"]["content_type"] in (
        "audio/wav", "audio/mpeg")  # mpeg when an ffmpeg binary is present
    result = run_smoke("cascade")
    assert "fatal_error" not in result
    assert result["pipeline_config"]["mode"] == "cascade_txt2img"


def test_smoke_txt2vid_ok():
    result = run_smoke("txt2vid")
    assert "fatal_error" not in result
    assert result["pipeline_config"]["mode"] == "txt2vid"
    assert result["artifacts"]["primary"]["content_type"] == "video/mp4"
    assert "thumbnail" in result["artifacts"]


def test_smoke_covers_every_routed_workflow():
    # the smoke matrix must keep pace with the dispatcher's routing table
    assert {"txt2img", "img2img", "txt2audio", "txt2vid", "img2txt",
            "cascade"} <= set(SMOKE_JOBS)


def test_init_fetches_catalog(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))

    async def scenario():
        hive = FakeHive()
        uri = await hive.start()
        hive.models = [{"name": "tiny", "family": "tiny",
                        "parameters": {"can_preload": False}}]
        monkeypatch.setenv("SDAAS_URI", uri)
        monkeypatch.setenv("SDAAS_TOKEN", "token")
        from chiaswarm_tpu.node.initialize import init

        code = await init(["--silent", "--no-prefetch"])
        await hive.stop()
        return code

    assert asyncio.run(scenario()) == 0
    catalog = json.loads((tmp_path / "models.json").read_text())
    assert catalog[0]["name"] == "tiny"
    settings = json.loads((tmp_path / "settings.json").read_text())
    assert settings["hive_token"] == "token"
