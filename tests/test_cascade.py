"""IF-class cascade: T5 encoder, converter naming, 2-stage pipeline, dispatch.

Reference behaviors covered: the three-stage DeepFloyd cascade with shared
prompt embeds (swarm/diffusion/diffusion_func_if.py:14-92) and the
``DeepFloyd/`` model-name routing (swarm/job_arguments.py:39-40).
"""

import numpy as np
import pytest

from chiaswarm_tpu.pipelines.cascade import (
    CASCADE_FAMILIES,
    CascadeComponents,
    CascadePipeline,
    get_cascade_family,
)


@pytest.fixture(scope="module")
def tiny_cascade():
    return CascadePipeline(CascadeComponents.random("tiny_cascade", seed=0))


def test_t5_encoder_forward():
    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.models.t5 import T5Config, T5Encoder

    cfg = T5Config(vocab_size=100, d_model=16, d_kv=4, d_ff=32,
                   num_layers=2, num_heads=4, max_length=12,
                   dtype="float32")
    enc = T5Encoder(cfg)
    ids = jnp.zeros((2, 12), jnp.int32)
    params = enc.init(jax.random.PRNGKey(0), ids)
    out = enc.apply(params, ids)
    assert out.shape == (2, 12, 16)
    assert np.isfinite(np.asarray(out)).all()
    # relative bias exists only in block 0 (shared downstream)
    assert "relative_attention_bias" in params["params"]["block_0"]["attention"]
    assert "relative_attention_bias" not in params["params"]["block_1"]["attention"]


def test_convert_t5_naming():
    from chiaswarm_tpu.convert.torch_to_flax import convert_t5

    state = {
        "shared.weight": np.zeros((100, 16)),
        "encoder.block.0.layer.0.SelfAttention.q.weight": np.zeros((16, 16)),
        "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight":
            np.zeros((32, 4)),
        "encoder.block.0.layer.0.layer_norm.weight": np.zeros((16,)),
        "encoder.block.0.layer.1.DenseReluDense.wi_0.weight": np.zeros((32, 16)),
        "encoder.block.0.layer.1.DenseReluDense.wo.weight": np.zeros((16, 32)),
        "encoder.block.0.layer.1.layer_norm.weight": np.zeros((16,)),
        "encoder.final_layer_norm.weight": np.zeros((16,)),
    }
    tree = convert_t5(state)["params"]
    assert tree["token_embedding"]["embedding"].shape == (100, 16)
    b0 = tree["block_0"]
    assert b0["attention"]["q"]["kernel"].shape == (16, 16)
    assert b0["attention"]["relative_attention_bias"].shape == (32, 4)
    assert b0["attn_norm"]["scale"].shape == (16,)
    assert b0["wi_0"]["kernel"].shape == (16, 32)
    assert b0["wo"]["kernel"].shape == (32, 16)
    assert b0["ff_norm"]["scale"].shape == (16,)
    assert tree["final_layer_norm"]["scale"].shape == (16,)


def test_cascade_family_routing():
    assert get_cascade_family("DeepFloyd/IF-I-XL-v1.0").name == "if_xl"
    assert get_cascade_family("random/tiny_cascade").name == "tiny_cascade"
    assert CASCADE_FAMILIES["if_xl"].stage1.cross_attention_dim == 4096


def test_cascade_two_stage_generation(tiny_cascade):
    img, config = tiny_cascade("a castle", steps=2, sr_steps=2, seed=4,
                               guidance_scale=5.0)
    fam = tiny_cascade.c.family
    assert img.shape == (1, fam.sr_size, fam.sr_size, 3)
    assert img.dtype == np.uint8
    assert config["mode"] == "cascade_txt2img"
    # determinism per seed
    img2, _ = tiny_cascade("a castle", steps=2, sr_steps=2, seed=4,
                           guidance_scale=5.0)
    assert np.array_equal(img, img2)
    img3, _ = tiny_cascade("a castle", steps=2, sr_steps=2, seed=5,
                           guidance_scale=5.0)
    assert not np.array_equal(img, img3)


@pytest.mark.slow
def test_cascade_workload_dispatch():
    """format_args routes DeepFloyd/ names to the cascade callback, which
    produces artifacts (upscale off to keep it tiny-model only)."""
    from chiaswarm_tpu.node.job_args import format_args
    from chiaswarm_tpu.node.registry import ModelRegistry

    registry = ModelRegistry(catalog=[], allow_random=True)
    job = {"model_name": "DeepFloyd/tiny_cascade", "prompt": "a boat",
           "num_inference_steps": 2, "sr_steps": 2, "seed": 9,
           "workflow": "txt2img"}
    callback, kwargs = format_args(job, registry)
    assert callback.__name__ == "cascade_callback"
    kwargs.pop("seed", None)
    artifacts, config = callback("slot0", kwargs.pop("model_name"),
                                 seed=9, upscale=False, **kwargs)
    assert "primary" in artifacts
    assert config["family"] == "tiny_cascade"
    assert config["images_per_sec"] > 0


@pytest.mark.slow
def test_cascade_three_stage_emits_4x_sr_size(tiny_cascade):
    """Full IF protocol: base -> sr -> latent-upscale passes to
    4 * sr_size (the reference's stage-3 x4-upscaler output,
    diffusion_func_if.py:31-40,63-65). Three denoise stages run and the
    final image is 4x the stage-2 size."""
    from chiaswarm_tpu.pipelines import Components
    from chiaswarm_tpu.pipelines.upscale import LatentUpscalePipeline

    upscaler = LatentUpscalePipeline(Components.random("tiny_up", seed=0))
    fam = tiny_cascade.c.family
    # final_size=2*sr keeps the hermetic run to ONE x2 pass (a 256px CPU
    # compile takes tens of minutes); the default (no final_size) is
    # 4 * sr_size = 1024px for the production IF family — the while-loop
    # target logic is identical either way
    img, config = tiny_cascade("a castle", steps=2, sr_steps=2, seed=4,
                               guidance_scale=5.0, upscaler=upscaler,
                               final_size=fam.sr_size * 2)
    assert img.shape == (1, fam.sr_size * 2, fam.sr_size * 2, 3)
    assert img.dtype == np.uint8
    assert config["stages"] == 3  # base, sr, upscale stage
    assert config["stage3_passes"] == 1
    assert config["size"] == [fam.sr_size * 2, fam.sr_size * 2]


@pytest.mark.slow
def test_cascade_stage3_x4_single_pass(tiny_cascade):
    """Stage 3 through the SD-x4-upscaler model class — the reference's
    actual stage 3 (diffusion_func_if.py:31-40): ONE pass takes sr_size
    to 4 * sr_size, conditioned on the prompt string and a noise level."""
    from chiaswarm_tpu.pipelines import Components
    from chiaswarm_tpu.pipelines.upscale import Upscale4xPipeline

    upscaler = Upscale4xPipeline(Components.random("tiny_up4", seed=0))
    fam = tiny_cascade.c.family
    img, config = tiny_cascade("a castle", steps=2, sr_steps=2, seed=4,
                               guidance_scale=5.0, upscaler=upscaler,
                               final_size=fam.sr_size * 4)
    assert img.shape == (1, fam.sr_size * 4, fam.sr_size * 4, 3)
    assert config["stages"] == 3
    assert config["stage3_passes"] == 1  # one x4 pass, not two x2 passes
    assert config["scale"] == 4
    assert config["size"] == [fam.sr_size * 4, fam.sr_size * 4]


@pytest.mark.slow
def test_cascade_stage_parallel_dispatch_and_placement():
    """Pipeline parallelism (SURVEY §2b): a multi-image job on a
    multi-chip slot runs stages 1+2 and stage 3 on DISJOINT submeshes
    (cascade_callback -> generate_stage_parallel). One callback pays the
    compiles; placement and reproducibility assertions reuse the
    registry's mesh-keyed residents."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import split_mesh
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.pipelines.cascade import generate_stage_parallel
    from chiaswarm_tpu.core.mesh import MeshSpec
    from chiaswarm_tpu.workloads.cascade import cascade_callback

    registry = ModelRegistry(catalog=[], allow_random=True)
    # two devices -> two SINGLE-device submeshes: the cheapest topology
    # that exercises the stage-parallel path (an 8-device pool splits
    # into 4-device submeshes whose GSPMD compiles cost minutes on the
    # virtual CPU mesh for zero extra coverage)
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 2}),
                    devices=jax.devices()[:2])
    artifacts, config = cascade_callback(
        pool.slots[0], "random/tiny_cascade", seed=5, registry=registry,
        prompt="a pier", num_inference_steps=2, sr_steps=2,
        num_images_per_prompt=2,
        upscaler_model_name="random/tiny_up", final_size=128)
    assert "primary" in artifacts
    assert config["pipeline_parallel"] == 2
    assert config["stages"] == 3
    assert config["size"] == [128, 128]

    # the callback placed each stage on its own submesh: these registry
    # fetches are LRU hits on the very objects it used
    base_mesh, up_mesh = split_mesh(pool.slots[0].mesh, 2)
    pipe = registry.cascade_pipeline("random/tiny_cascade",
                                     mesh=base_mesh)
    upscaler = registry.pipeline("random/tiny_up", mesh=up_mesh)

    def devices_of(params):
        out = set()
        for leaf in jax.tree.leaves(params):
            out |= set(leaf.devices())
        return out

    base_devs = devices_of(pipe.c.params)
    up_devs = devices_of(upscaler.c.params)
    assert base_devs and up_devs and not (base_devs & up_devs), (
        base_devs, up_devs)

    # per-(seed, index) reproducibility: image i depends only on its own
    # folded seed (cached executables make these runs cheap)
    imgs_a, _ = generate_stage_parallel(
        pipe, upscaler, prompt="a pier", steps=2, sr_steps=2,
        guidance_scale=5.0, n_images=2, seed=5, final_size=128)
    imgs_b, _ = generate_stage_parallel(
        pipe, upscaler, prompt="a pier", steps=2, sr_steps=2,
        guidance_scale=5.0, n_images=2, seed=5, final_size=128)
    assert (imgs_a == imgs_b).all()


@pytest.mark.slow
def test_cascade_workload_three_stage_dispatch():
    """cascade_callback with upscale=True (the default) runs stage 3
    through the registry's upscaler and reports the upscaled size."""
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.workloads.cascade import cascade_callback

    registry = ModelRegistry(catalog=[], allow_random=True)
    artifacts, config = cascade_callback(
        "slot0", "random/tiny_cascade", seed=3, registry=registry,
        prompt="a boat", num_inference_steps=2, sr_steps=2,
        upscaler_model_name="random/tiny_up", final_size=128)
    assert "primary" in artifacts
    assert config["size"][0] == config["size"][1] == 128  # 64 * 2
    assert config["stages"] == 3
    assert "nsfw" in config
