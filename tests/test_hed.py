"""HED edge-detector tests: torch-reference fidelity + preprocessor wiring.

The reference's scribble/softedge modes run controlnet_aux's HEDdetector
(swarm/controlnet/input_processor.py:17-60); these pin the native port
(models/hed.py) to the same graph and the weight-gated fallback behavior.
"""

from __future__ import annotations

import numpy as np
import pytest

from chiaswarm_tpu.models.hed import HEDDetector


def _torch_hed():
    """Independent torch construction of the ControlNetHED graph."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    class DoubleConvBlock(nn.Module):
        def __init__(self, cin, cout, n):
            super().__init__()
            self.convs = nn.ModuleList(
                [nn.Conv2d(cin if i == 0 else cout, cout, 3, padding=1)
                 for i in range(n)])
            self.projection = nn.Conv2d(cout, 1, 1)

        def forward(self, x):
            for conv in self.convs:
                x = torch.relu(conv(x))
            return x, self.projection(x)

    class HED(nn.Module):
        def __init__(self):
            super().__init__()
            self.norm = nn.Parameter(torch.zeros(1, 3, 1, 1))
            self.block1 = DoubleConvBlock(3, 64, 2)
            self.block2 = DoubleConvBlock(64, 128, 2)
            self.block3 = DoubleConvBlock(128, 256, 3)
            self.block4 = DoubleConvBlock(256, 512, 3)
            self.block5 = DoubleConvBlock(512, 512, 3)

        def forward(self, x):
            h = x - self.norm
            sides = []
            for b in (self.block1, self.block2, self.block3, self.block4,
                      self.block5):
                if sides:
                    h = torch.nn.functional.max_pool2d(h, 2, 2)
                h, side = b(h)
                sides.append(side)
            return sides

    torch.manual_seed(0)
    net = HED().eval()
    with torch.no_grad():
        net.norm.copy_(torch.tensor([103.9, 116.8, 123.7]
                                    ).view(1, 3, 1, 1))
    return torch, net


def test_conversion_matches_torch_reference():
    torch, net = _torch_hed()
    import jax.numpy as jnp

    from chiaswarm_tpu.convert.torch_to_flax import convert_hed

    state = {k: v.detach().numpy() for k, v in net.state_dict().items()}
    det = HEDDetector(params=convert_hed(state))
    x = (np.random.RandomState(0).rand(1, 32, 32, 3) * 255).astype(
        np.float32)
    with torch.no_grad():
        tsides = net(torch.from_numpy(x.transpose(0, 3, 1, 2)))
    fsides = det._fwd(det.params, jnp.asarray(x))
    for i, (ts, fs) in enumerate(zip(tsides, fsides)):
        np.testing.assert_allclose(
            np.asarray(fs)[..., 0], ts.numpy()[:, 0], atol=2e-3,
            rtol=2e-3, err_msg=f"side {i}")


def test_converter_rejects_wrong_state():
    from chiaswarm_tpu.convert.torch_to_flax import convert_hed

    with pytest.raises(ValueError, match="expected 5"):
        convert_hed({"norm": np.zeros((1, 3, 1, 1)),
                     "block1.convs.0.weight": np.zeros((64, 3, 3, 3))})


def test_detector_runs_on_odd_sizes():
    det = HEDDetector.random(seed=0, canvas=64)
    img = (np.random.RandomState(1).rand(37, 53, 3) * 255).astype(np.uint8)
    edge = det(img)
    assert edge.shape == (37, 53) and edge.dtype == np.uint8


def test_softedge_uses_hed_when_weights_present(monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setattr(wl, "_HED", [HEDDetector.random(seed=2, canvas=64)])
    out = wl.preprocess_image(Image.new("RGB", (64, 48), (90, 120, 40)),
                              {"type": "softedge", "preprocess": True})
    arr = np.asarray(out)
    assert arr.shape == (48, 64, 3)


def test_softedge_falls_back_without_weights(tmp_path, monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    monkeypatch.setattr(wl, "_HED", [])
    out = wl.preprocess_image(Image.new("RGB", (64, 48), (90, 120, 40)),
                              {"type": "scribble", "preprocess": True})
    assert np.asarray(out).shape == (48, 64, 3)
    assert wl._HED == [None]  # stand-in path cached
