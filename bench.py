"""Driver benchmark entry: prints ONE JSON line with the north-star metric
(see chiaswarm_tpu/benchmark.py for the implementation and knobs)."""

from chiaswarm_tpu.benchmark import main

if __name__ == "__main__":
    main()
