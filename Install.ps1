# swarm-tpu installer for Windows development hosts (parity with the
# reference's Install.ps1 venv bootstrap, /root/reference/Install.ps1:1-104).
#
# Windows machines have no TPU: this sets up the CPU jax backend, which
# runs the full hermetic test suite, the smoke harness, and the virtual
# multi-chip mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8) for
# development. Production serving runs on TPU VMs via install.sh/Docker.

$ErrorActionPreference = "Stop"

if (-not [Environment]::Is64BitOperatingSystem) {
    Write-Error "swarm-tpu requires a 64-bit Windows installation"
    Exit 1
}

# Check for Python
try {
    $pythonVersion = (python --version).split(" ")[1]
}
catch {
    Write-Error "Unable to find python"
    Write-Output "Install Python 3.10+ from: https://docs.python.org/3/using/windows.html#installation-steps"
    Exit 1
}

$parts = $pythonVersion.split(".")
if ([int]$parts[0] -lt 3 -or ([int]$parts[0] -eq 3 -and [int]$parts[1] -lt 10)) {
    Write-Error "swarm-tpu requires Python 3.10+ (found $pythonVersion)"
    Exit 1
}

$venvDir = if ($env:VENV_DIR) { $env:VENV_DIR } else { ".venv" }

Write-Output "==> creating venv at $venvDir"
python -m venv $venvDir
& "$venvDir\Scripts\Activate.ps1"
python -m pip install --upgrade pip | Out-Null

Write-Output "==> installing swarm-tpu (cpu backend; deps from pyproject.toml)"
pip install -e ".[cpu,test]"

Write-Output ""
Write-Output "Install complete. Next steps:"
Write-Output "  .\$venvDir\Scripts\Activate.ps1"
Write-Output "  python -m chiaswarm_tpu.cli init      # configure hive + fetch models"
Write-Output "  python -m chiaswarm_tpu.node.smoke --all --random-weights"
Write-Output "  python -m pytest tests\ -q            # hermetic suite (CPU)"
