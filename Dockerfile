# swarm-tpu worker image (parity with the reference's Dockerfile, which
# ships a CUDA torch base + ffmpeg and bind-mounts the HF cache;
# /root/reference Dockerfile:1-43). TPU differences: the base carries
# jax[tpu] instead of torch+cu118, libtpu comes from the TPU VM runtime,
# and the native artifact codec builds at image build time.

FROM python:3.12-slim-bookworm

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ zlib1g-dev libgl1 libglib2.0-0 ffmpeg \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/swarm-tpu
COPY pyproject.toml ./
COPY chiaswarm_tpu ./chiaswarm_tpu
COPY csrc ./csrc
COPY bench.py ./

# deps come from pyproject.toml; the [tpu] extra resolves libtpu for TPU
# VMs (on other hosts the base jax wheel's CPU backend runs)
RUN pip install --no-cache-dir -e ".[tpu]" \
        -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

# pre-build the native artifact codec (chiaswarm_tpu/native builds it on
# first use otherwise)
RUN python -c "from chiaswarm_tpu import native; assert native.load()"

# config + model cache live outside the image, like the reference's
# HF-cache bind mount (Dockerfile:28-37)
ENV SDAAS_ROOT=/data
VOLUME /data

ENTRYPOINT ["python", "-m", "chiaswarm_tpu.cli"]
CMD ["worker"]
