"""Per-fusion roofline table for the headline SDXL-1024 denoise program.

Thin CLI over ``chiaswarm_tpu.obs.hlocost`` (swarmlens, ISSUE 11) — the
HLO cost model, the profiler join, and the attainment math all live in
the library now, where ``benchmark.py`` stamps them into BENCH json and
``tests/test_op_roofline.py`` costs canned HLO fixtures without a TPU.
This script keeps the operator workflow:

VERDICT r2 item #2's alternative "done" criterion: show, per conv
fusion, how close the compiled program runs to ITS OWN roofline — the
max of its compute time (FLOPs / peak MXU throughput) and its memory
time (HBM bytes / peak bandwidth). A fusion near 100% of that bound has
no headroom left in user code; a fusion far below it marks where XLA's
conv scheduling leaves time on the table.

Method (no TF/tensorboard dependency; works through the axon tunnel,
where ``--xla_dump_to`` would land on the far side):
1. patch the pipelines' ``toplevel_jit`` with the library's AOT-capturing
   :class:`~chiaswarm_tpu.obs.hlocost.ProgramCapture`, so the generate
   program's LoadedExecutable is in hand and its scheduled HLO readable;
2. profile ONE generate call with ``jax.profiler.trace`` and read the
   device plane's per-HLO-op durations (while-loop body ops appear once
   per denoise step, so counts fold the 30 steps in);
3. statically cost each fusion from that HLO;
4. print achieved TFLOP/s, both roofline components, and percent-of-
   roofline per fusion, heaviest first, plus program totals.

Usage (real chip):
    python tools/op_roofline.py [--steps 30] [--size 1024] [--family sdxl]
Peak numbers default to TPU v5e (197 bf16 TFLOP/s, 819 GB/s) and are
overridable via CHIASWARM_PEAK_TFLOPS / CHIASWARM_PEAK_GBPS for other
generations. Results belong in BASELINE.md — and, since ISSUE 11, ride
every BENCH run as the per-config ``roofline`` block.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from chiaswarm_tpu.obs.hlocost import (  # noqa: E402
    ProgramCapture,
    attainment_rows,
    collect_op_times,
    compiled_hlo_text,
    conv_attainment_summary,
    default_peaks,
    parse_hlo_text,
)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--family", default=os.environ.get(
        "CHIASWARM_BENCH_FAMILY", "sdxl"))
    parser.add_argument("--size", type=int, default=1024)
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--top", type=int, default=30)
    parser.add_argument("--controlnet", action="store_true",
                        help="profile the combined ControlNet+UNet program "
                             "(BASELINE.json config #4) instead of the base "
                             "generate program")
    parser.add_argument("--img2vid", action="store_true",
                        help="profile the SVD img2vid program (config #5: "
                             "spatio-temporal UNet + temporal-decoder VAE) "
                             "at --size x --size; use --width for the "
                             "published 576x1024 portrait")
    parser.add_argument("--width", type=int, default=None)
    parser.add_argument("--frames", type=int, default=14)
    args = parser.parse_args()

    import jax

    peak_tflops, peak_gbps = default_peaks()

    import chiaswarm_tpu.pipelines.diffusion as diffusion_mod
    from chiaswarm_tpu.core import compat
    from chiaswarm_tpu.pipelines.components import Components
    from chiaswarm_tpu.pipelines.diffusion import (
        DiffusionPipeline,
        GenerateRequest,
    )

    capture = ProgramCapture()
    on_tpu = jax.default_backend() == "tpu"
    size = args.size if on_tpu else 64
    steps = args.steps if on_tpu else 2

    if args.img2vid:
        import numpy as np

        import chiaswarm_tpu.pipelines.video as video_mod
        from chiaswarm_tpu.pipelines.video import (
            Img2VidPipeline,
            VideoComponents,
        )

        with capture.patching(diffusion_mod, video_mod):
            fam = "svd_img2vid" if on_tpu else "tiny_svd"
            vc = VideoComponents.random_host(fam, seed=0)
            vc.params = jax.device_put(vc.params, jax.devices()[0])
            ipipe = Img2VidPipeline(vc)
            height = size
            width = args.width or size
            frames = args.frames if on_tpu else 4
            cond = np.random.default_rng(0).integers(
                0, 255, (height, width, 3), dtype=np.uint8)
            print(f"compiling img2vid {height}x{width} {frames}f {steps} "
                  f"steps ...", file=sys.stderr)
            ipipe(cond, num_frames=frames, steps=steps, height=height,
                  width=width, seed=0)  # compile + warm
            trace_dir = tempfile.mkdtemp(prefix="xplane_")
            with compat.profiler_trace(trace_dir):
                ipipe(cond, num_frames=frames, steps=steps, height=height,
                      width=width, seed=0)
        _report(trace_dir, capture, args, peak_tflops, peak_gbps)
        return

    family = args.family if on_tpu else "tiny"

    with capture.patching(diffusion_mod):
        c = Components.random_host(family, seed=0)
        c.params = jax.device_put(c.params, jax.devices()[0])
        pipe = DiffusionPipeline(c)
        controlnet = control_image = None
        if args.controlnet:
            import numpy as np

            from chiaswarm_tpu.pipelines.components import ControlNetBundle

            controlnet = ControlNetBundle.random_host(family, seed=1)
            controlnet.params = jax.device_put(controlnet.params,
                                               jax.devices()[0])
            control_image = np.random.default_rng(0).integers(
                0, 255, (size, size, 3), dtype=np.uint8)
        req = GenerateRequest(prompt="roofline probe", steps=steps,
                              height=size, width=size, batch=1, seed=0,
                              guidance_scale=7.0, controlnet=controlnet,
                              control_image=control_image)
        print(f"compiling {family}"
              f"{'+controlnet' if args.controlnet else ''} "
              f"{size}px {steps} steps ...", file=sys.stderr)
        pipe(req)  # compile + warm

        trace_dir = tempfile.mkdtemp(prefix="xplane_")
        with compat.profiler_trace(trace_dir):
            pipe(req)
    _report(trace_dir, capture, args, peak_tflops, peak_gbps)


def _report(trace_dir, capture: ProgramCapture, args,
            peak_tflops, peak_gbps) -> None:
    xplane = glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True)
    if not xplane:
        raise FileNotFoundError("profiler produced no xplane.pb")

    times = collect_op_times(xplane[0])
    if not capture.executables:
        raise RuntimeError("no toplevel program captured")
    hlo_text = max(
        (compiled_hlo_text(compiled) for compiled in capture.executables),
        key=len)
    costs = parse_hlo_text(hlo_text)
    rows = attainment_rows(times, costs, peak_tflops=peak_tflops,
                           peak_gbps=peak_gbps)
    summary = conv_attainment_summary(rows)

    print(f"\ndevice op time total (containers excluded): "
          f"{summary['total_ms']:.1f} ms; conv fusions: "
          f"{summary['conv_ms']:.1f} ms "
          f"({summary['conv_share_pct']:.0f}%), "
          f"time-weighted conv roofline attainment: "
          f"{summary['weighted_conv_roof_pct']:.0f}% "
          f"over {summary['sane_ms']:.1f} ms"
          + (f" ({summary['miscosted_fusions']} fusions excluded as "
             f"mis-costed, {summary['miscosted_ms']:.1f} ms)"
             if summary["miscosted_fusions"] else ""))
    print(f"peaks: {peak_tflops:.0f} TFLOP/s, {peak_gbps:.0f} GB/s "
          f"(CHIASWARM_PEAK_TFLOPS/GBPS to override)\n")
    header = (f"{'op':<40} {'kind':>5} {'n':>4} {'ms':>8} {'GFLOP':>9} "
              f"{'MB':>8} {'TFLOP/s':>8} {'bound':>5} {'%roof':>6} "
              f"{'%time':>6}")
    print(header)
    print("-" * len(header))
    for r in rows[: args.top]:
        print(f"{r['name'][:40]:<40} {r['kind']:>5} {r['count']:>4} "
              f"{r['ms']:>8.2f} {r['gflop']:>9.1f} {r['mb']:>8.1f} "
              f"{r['tflops']:>8.1f} {r['bound']:>5} {r['roof_pct']:>6.0f} "
              f"{r['share_pct']:>6.1f}")


if __name__ == "__main__":
    main()
