"""Per-fusion roofline table for the headline SDXL-1024 denoise program.

VERDICT r2 item #2's alternative "done" criterion: show, per conv fusion,
how close the compiled program runs to ITS OWN roofline — the max of its
compute time (FLOPs / peak MXU throughput) and its memory time (HBM bytes
/ peak bandwidth). A fusion near 100% of that bound has no headroom left
in user code; a fusion far below it marks where XLA's conv scheduling
leaves time on the table.

Method (no TF/tensorboard dependency; works through the axon tunnel,
where ``--xla_dump_to`` would land on the far side):
1. patch the pipelines' ``toplevel_jit`` with an AOT-capturing wrapper,
   so the generate program's LoadedExecutable is in hand and
   ``runtime_executable().get_hlo_text()`` yields the exact scheduled HLO
   the chip runs;
2. profile ONE generate call with ``jax.profiler.trace`` and read the
   device plane's "XLA Ops" line via ``jax.profiler.ProfileData`` —
   per-HLO-op device durations and occurrence counts (while-loop body ops
   appear once per denoise step, so counts fold the 30 steps in);
3. statically cost each fusion from that HLO: conv FLOPs from
   window/dim_labels/feature_group_count, dot FLOPs from contracting
   dims, HBM bytes from the fusion signature's operand+result shapes;
4. print achieved TFLOP/s, both roofline components, and percent-of-
   roofline per fusion, heaviest first, plus program totals.

Usage (real chip):
    python tools/op_roofline.py [--steps 30] [--size 1024] [--family sdxl]
Peak numbers default to TPU v5e (197 bf16 TFLOP/s, 819 GB/s) and are
overridable via CHIASWARM_PEAK_TFLOPS / CHIASWARM_PEAK_GBPS for other
generations. Results belong in BASELINE.md.
"""

from __future__ import annotations

import argparse
import glob
import math
import os
import re
import sys
import tempfile

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
}

_SHAPE_RE = re.compile(r"\b(pred|[su]\d+|bf16|f16|f32|f64)\[([\d,]*)\]")
_NAME_RE = re.compile(r"%([\w.-]+)")
_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*(.+)$")


def _shape_dims(dtype_dims: tuple[str, str]):
    dtype, dims = dtype_dims
    return dtype, [int(d) for d in dims.split(",") if d]


def _shape_bytes(dtype: str, dims: list[int]) -> int:
    return math.prod(dims, start=1) * _DTYPE_BYTES.get(dtype, 4)


def build_shape_map(text: str) -> dict[str, tuple[str, list[int]]]:
    """instruction name -> (dtype, dims) of its (first) result shape.

    Scheduled HLO prints operands as bare ``%names`` (no inline shapes),
    so operand shapes must be resolved through the defining instruction.
    """
    shape_map: dict[str, tuple[str, list[int]]] = {}
    for line in text.splitlines():
        d = _DEF_RE.match(line)
        if not d:
            continue
        m = _SHAPE_RE.search(d.group(2))
        if m:
            shape_map[d.group(1)] = _shape_dims(m.groups())
    return shape_map


def _operand_shapes(line: str, opcode: str,
                    shape_map) -> list[tuple[str, list[int]]]:
    """(dtype, dims) of each operand of ``opcode`` on ``line`` — inline
    shapes when the printer emitted them, the definition map otherwise."""
    start = line.find(opcode + "(")
    if start < 0:
        return []
    seg = line[start + len(opcode) + 1:]
    # the operand list ends at the first ")" outside {} layout braces and
    # outside nested "(" groups (tuple-typed inline shapes)
    brace = paren = 0
    end = len(seg)
    for i, ch in enumerate(seg):
        if ch == "{":
            brace += 1
        elif ch == "}":
            brace -= 1
        elif brace == 0 and ch == "(":
            paren += 1
        elif brace == 0 and ch == ")":
            if paren:
                paren -= 1
            else:
                end = i
                break
    seg = seg[:end]
    inline = _SHAPE_RE.findall(seg)
    names = _NAME_RE.findall(seg)
    if inline and len(inline) >= len(names):
        return [_shape_dims(s) for s in inline]
    return [shape_map[n] for n in names if n in shape_map]


def _conv_flops(line: str, shape_map) -> float:
    """FLOPs of one HLO convolution instruction (per execution):
    2 * out_elems * window_elems * in_features / feature_group_count."""
    m = _SHAPE_RE.search(line.split("=", 1)[-1])
    if not m:
        return 0.0
    _, out_dims = _shape_dims(m.groups())
    out_elems = math.prod(out_dims, start=1)

    window = re.search(r"window={[^}]*?size=([\dx]+)", line)
    window_elems = 1
    if window:
        for d in window.group(1).split("x"):
            window_elems *= int(d)

    labels = re.search(r"dim_labels=(\S+?)->", line)
    groups = re.search(r"feature_group_count=(\d+)", line)
    group_n = int(groups.group(1)) if groups else 1

    in_features = 1
    operands = _operand_shapes(line, "convolution", shape_map)
    if labels and len(operands) >= 2:
        lhs_rhs = labels.group(1).split("_")
        if len(lhs_rhs) == 2:
            rhs_spec = lhs_rhs[1]  # e.g. "01io"
            rhs_dims = operands[1][1]
            i_pos = rhs_spec.find("i")
            if 0 <= i_pos < len(rhs_dims):
                in_features = rhs_dims[i_pos]
    return 2.0 * out_elems * window_elems * in_features / group_n


def _dot_flops(line: str, shape_map) -> float:
    """FLOPs of one HLO dot: 2 * out_elems * prod(contracting dims)."""
    m = _SHAPE_RE.search(line.split("=", 1)[-1])
    if not m:
        return 0.0
    _, out_dims = _shape_dims(m.groups())
    out_elems = math.prod(out_dims, start=1)
    contract = re.search(r"lhs_contracting_dims={([\d,]*)}", line)
    operands = _operand_shapes(line, "dot", shape_map)
    k = 1
    if contract and contract.group(1) and operands:
        lhs_dims = operands[0][1]
        for idx in contract.group(1).split(","):
            i = int(idx)
            if i < len(lhs_dims):
                k *= lhs_dims[i]
    return 2.0 * out_elems * k


def _flash_flops(line: str, shape_map) -> float:
    """Attention FLOPs of a flash custom call: 2*BH*L*S*D for QK^T plus
    the same for PV — 4*BH*L*S*D. The kernel folds heads into the lead
    dim and pads L/S to its block lattice, so operands are
    (B*H, L_pad, D) (ops/flash_attention.py) — padded work is real
    compute and is costed as such."""
    operands = [dims for _, dims in
                _operand_shapes(line, "custom-call", shape_map)
                if len(dims) == 3]
    if len(operands) < 2:
        return 0.0
    bh, l, d = operands[0]
    s = operands[1][1]
    return 4.0 * bh * l * s * d


def _io_bytes(line: str, opcode: str, shape_map) -> int:
    """HBM traffic estimate of one instruction: result + operand shapes,
    each touched once."""
    total = 0
    m = _SHAPE_RE.search(line.split("=", 1)[-1])
    if m:
        total += _shape_bytes(*_shape_dims(m.groups()))
    for dtype, dims in _operand_shapes(line, opcode, shape_map):
        total += _shape_bytes(dtype, dims)
    return total


def parse_hlo_text(text: str) -> dict[str, dict]:
    """fusion/conv/dot name -> {flops, bytes, kind} from scheduled HLO."""
    shape_map = build_shape_map(text)

    # computation name -> [total conv+dot flops inside it, kind]
    comp_flops: dict[str, list] = {}
    current = None
    for line in text.splitlines():
        header = re.match(
            r"\s*(?:ENTRY\s+)?%?([\w.-]+)\s+\(.*\)\s*->\s*.+\{\s*$", line)
        if header:
            current = header.group(1)
            continue
        if line.startswith("}"):
            current = None
            continue
        if current is None:
            continue
        if " convolution(" in line:
            entry = comp_flops.setdefault(current, [0.0, "conv"])
            entry[0] += _conv_flops(line, shape_map)
        elif re.search(r"\bdot\(", line):
            entry = comp_flops.setdefault(current, [0.0, "dot"])
            entry[0] += _dot_flops(line, shape_map)
            if entry[1] == "conv":
                entry[1] = "mixed"

    fusions: dict[str, dict] = {}
    for line in text.splitlines():
        m = re.match(r"\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*.*?\bfusion\(",
                     line)
        if not m:
            # bare convs/dots outside fusions still deserve a row
            b = re.match(
                r"\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*.*?\b"
                r"(convolution|dot)\(", line)
            if b:
                op = b.group(2)
                flops = (_conv_flops(line, shape_map)
                         if op == "convolution"
                         else _dot_flops(line, shape_map))
                fusions[b.group(1)] = {
                    "flops": flops,
                    "bytes": _io_bytes(line, op, shape_map),
                    "kind": "conv" if op == "convolution" else "dot"}
            elif "custom-call" in line and "flash_attention" in line:
                c = re.match(r"\s*(?:ROOT\s+)?%?([\w.-]+)\s*=", line)
                if c:
                    fusions[c.group(1)] = {
                        "flops": _flash_flops(line, shape_map),
                        "bytes": _io_bytes(line, "custom-call", shape_map),
                        "kind": "flash"}
            continue
        name = m.group(1)
        called = re.search(r"calls=%?([\w.-]+)", line)
        flops, kind = 0.0, "other"
        if called and called.group(1) in comp_flops:
            flops, kind = comp_flops[called.group(1)]
        # HBM traffic estimate: every operand + the result, touched once
        # (fusions stream operands from HBM exactly once)
        fusions[name] = {"flops": flops,
                         "bytes": _io_bytes(line, "fusion", shape_map),
                         "kind": kind}
    return fusions


def collect_op_times(xplane_path: str) -> dict[str, dict]:
    """op name -> {total_ps, count} from the TPU device plane."""
    from jax.profiler import ProfileData

    pd = ProfileData.from_file(xplane_path)
    times: dict[str, dict] = {}
    for plane in pd.planes:
        if not plane.name.startswith("/device:TPU"):
            continue
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for event in line.events:
                stats = dict(event.stats)
                dur = stats.get("device_duration_ps")
                if dur is None:
                    continue
                name = event.name.split(" = ")[0].lstrip("%")
                entry = times.setdefault(
                    name, {"total_ps": 0, "count": 0,
                           "signature": event.name})
                entry["total_ps"] += int(dur)
                entry["count"] += 1
    return times


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--family", default=os.environ.get(
        "CHIASWARM_BENCH_FAMILY", "sdxl"))
    parser.add_argument("--size", type=int, default=1024)
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--top", type=int, default=30)
    parser.add_argument("--controlnet", action="store_true",
                        help="profile the combined ControlNet+UNet program "
                             "(BASELINE.json config #4) instead of the base "
                             "generate program")
    parser.add_argument("--img2vid", action="store_true",
                        help="profile the SVD img2vid program (config #5: "
                             "spatio-temporal UNet + temporal-decoder VAE) "
                             "at --size x --size; use --width for the "
                             "published 576x1024 portrait")
    parser.add_argument("--width", type=int, default=None)
    parser.add_argument("--frames", type=int, default=14)
    args = parser.parse_args()

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import jax

    peak_tflops = float(os.environ.get("CHIASWARM_PEAK_TFLOPS", "197"))
    peak_gbps = float(os.environ.get("CHIASWARM_PEAK_GBPS", "819"))

    import chiaswarm_tpu.pipelines.diffusion as diffusion_mod
    from chiaswarm_tpu.core import compat
    from chiaswarm_tpu.pipelines.components import Components
    from chiaswarm_tpu.pipelines.diffusion import (
        DiffusionPipeline,
        GenerateRequest,
    )

    # AOT-capture every toplevel program the pipeline builds so the exact
    # scheduled HLO is readable afterward (the pipeline imported the name
    # at module load, so patch the module attribute, not compile_cache)
    real_toplevel_jit = diffusion_mod.toplevel_jit
    executables: list = []

    def capturing_toplevel_jit(fn, **kwargs):
        jitted = real_toplevel_jit(fn, **kwargs)
        slot = {"compiled": None}

        def wrapper(*args):
            if slot["compiled"] is None:
                slot["compiled"] = jitted.lower(*args).compile()
                executables.append(slot)
            return slot["compiled"](*args)

        return wrapper

    diffusion_mod.toplevel_jit = capturing_toplevel_jit

    on_tpu = jax.default_backend() == "tpu"
    size = args.size if on_tpu else 64
    steps = args.steps if on_tpu else 2

    if args.img2vid:
        import numpy as np

        import chiaswarm_tpu.pipelines.video as video_mod
        from chiaswarm_tpu.pipelines.video import (
            Img2VidPipeline,
            VideoComponents,
        )

        video_mod.toplevel_jit = capturing_toplevel_jit
        fam = "svd_img2vid" if on_tpu else "tiny_svd"
        vc = VideoComponents.random_host(fam, seed=0)
        vc.params = jax.device_put(vc.params, jax.devices()[0])
        ipipe = Img2VidPipeline(vc)
        height = size
        width = args.width or size
        frames = args.frames if on_tpu else 4
        cond = np.random.default_rng(0).integers(
            0, 255, (height, width, 3), dtype=np.uint8)
        print(f"compiling img2vid {height}x{width} {frames}f {steps} "
              f"steps ...", file=sys.stderr)
        ipipe(cond, num_frames=frames, steps=steps, height=height,
              width=width, seed=0)  # compile + warm
        trace_dir = tempfile.mkdtemp(prefix="xplane_")
        with compat.profiler_trace(trace_dir):
            ipipe(cond, num_frames=frames, steps=steps, height=height,
                  width=width, seed=0)
        _report(trace_dir, executables, args, peak_tflops, peak_gbps)
        return

    family = args.family if on_tpu else "tiny"

    c = Components.random_host(family, seed=0)
    c.params = jax.device_put(c.params, jax.devices()[0])
    pipe = DiffusionPipeline(c)
    controlnet = control_image = None
    if args.controlnet:
        import numpy as np

        from chiaswarm_tpu.pipelines.components import ControlNetBundle

        controlnet = ControlNetBundle.random_host(family, seed=1)
        controlnet.params = jax.device_put(controlnet.params,
                                           jax.devices()[0])
        control_image = np.random.default_rng(0).integers(
            0, 255, (size, size, 3), dtype=np.uint8)
    req = GenerateRequest(prompt="roofline probe", steps=steps,
                          height=size, width=size, batch=1, seed=0,
                          guidance_scale=7.0, controlnet=controlnet,
                          control_image=control_image)
    print(f"compiling {family}{'+controlnet' if args.controlnet else ''} "
          f"{size}px {steps} steps ...", file=sys.stderr)
    pipe(req)  # compile + warm

    trace_dir = tempfile.mkdtemp(prefix="xplane_")
    with compat.profiler_trace(trace_dir):
        pipe(req)
    _report(trace_dir, executables, args, peak_tflops, peak_gbps)


def _report(trace_dir, executables, args, peak_tflops, peak_gbps) -> None:
    xplane = glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True)
    if not xplane:
        raise FileNotFoundError("profiler produced no xplane.pb")

    times = collect_op_times(xplane[0])
    if not executables:
        raise RuntimeError("no toplevel program captured")
    hlo_text = max(
        (s["compiled"].runtime_executable().get_hlo_text()
         for s in executables), key=len)
    costs = parse_hlo_text(hlo_text)

    def is_container(name: str) -> bool:
        # a while/conditional event SPANS its body ops, which also appear
        # on the same line — counting both would double-book the time
        return name.split(".")[0] in ("while", "conditional", "call")

    rows = []
    total_ps = sum(t["total_ps"] for name, t in times.items()
                   if not is_container(name))
    for name, t in times.items():
        if is_container(name):
            continue
        cost = costs.get(name) or {}
        secs = t["total_ps"] * 1e-12
        flops = cost.get("flops", 0.0) * t["count"]
        bts = cost.get("bytes", 0) * t["count"]
        t_compute = flops / (peak_tflops * 1e12)
        t_bw = bts / (peak_gbps * 1e9)
        t_roof = max(t_compute, t_bw)
        kind = cost.get("kind", "other")
        if kind == "other" and "flash" in name:
            kind = "flash"
        rows.append({
            "name": name, "kind": kind, "count": t["count"],
            "ms": secs * 1e3,
            "gflop": flops / 1e9, "mb": bts / 1e6,
            "tflops": (flops / secs / 1e12) if secs else 0.0,
            "bound": "flops" if t_compute >= t_bw else "hbm",
            "roof_pct": (100.0 * t_roof / secs) if secs else 0.0,
            "share_pct": 100.0 * t["total_ps"] / max(total_ps, 1),
        })
    rows.sort(key=lambda r: -r["ms"])

    conv_rows = [r for r in rows if r["kind"] in ("conv", "mixed")]
    conv_ms = sum(r["ms"] for r in conv_rows)
    # a fusion whose static cost model exceeds its measured time by >1.2x
    # is MIS-COSTED (e.g. a multi-conv fusion double-counted, or a
    # rematerialized op the profiler books elsewhere) — folding it into
    # the attainment average would report >100% nonsense; report it
    # separately instead
    sane = [r for r in conv_rows if r["roof_pct"] <= 120.0]
    sane_ms = sum(r["ms"] for r in sane)
    weighted_roof = (sum(r["roof_pct"] * r["ms"] for r in sane)
                     / max(sane_ms, 1e-9))
    n_miscosted = len(conv_rows) - len(sane)

    print(f"\ndevice op time total (containers excluded): "
          f"{total_ps * 1e-9:.1f} ms; conv fusions: {conv_ms:.1f} ms "
          f"({100 * conv_ms / max(total_ps * 1e-9, 1e-9):.0f}%), "
          f"time-weighted conv roofline attainment: {weighted_roof:.0f}% "
          f"over {sane_ms:.1f} ms"
          + (f" ({n_miscosted} fusions excluded as mis-costed, "
             f"{conv_ms - sane_ms:.1f} ms)" if n_miscosted else ""))
    print(f"peaks: {peak_tflops:.0f} TFLOP/s, {peak_gbps:.0f} GB/s "
          f"(CHIASWARM_PEAK_TFLOPS/GBPS to override)\n")
    header = (f"{'op':<40} {'kind':>5} {'n':>4} {'ms':>8} {'GFLOP':>9} "
              f"{'MB':>8} {'TFLOP/s':>8} {'bound':>5} {'%roof':>6} "
              f"{'%time':>6}")
    print(header)
    print("-" * len(header))
    for r in rows[: args.top]:
        print(f"{r['name'][:40]:<40} {r['kind']:>5} {r['count']:>4} "
              f"{r['ms']:>8.2f} {r['gflop']:>9.1f} {r['mb']:>8.1f} "
              f"{r['tflops']:>8.1f} {r['bound']:>5} {r['roof_pct']:>6.0f} "
              f"{r['share_pct']:>6.1f}")


if __name__ == "__main__":
    main()
