"""Micro-benchmark the Pallas flash-attention kernel across block sizes.

Times the kernel alone (no UNet) at a given (B, L, H, D) self-attention
shape on the real chip, for a list of (block_q, block_kv) candidates.
Used to tune `_pick_block` (ops/flash_attention.py) for non-power-of-two
serving levels — e.g. the SVD portrait's 2304- and 9216-token spatial
levels, where the roofline showed 49% / 69% attainment with the
auto-picked blocks (tools/roofline_img2vid_r5_shortcut.txt).

    python tools/flash_sweep.py --batch 28 --seq 2304 --heads 10 \
        --blocks 768x768,1152x1152,1152x2304,2304x1024

Prints one line per candidate: median ms over --iters and achieved
TFLOP/s (4*B*H*L^2*D flops).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=28)
    ap.add_argument("--seq", type=int, default=2304)
    ap.add_argument("--heads", type=int, default=10)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--iters", type=int, default=7)
    ap.add_argument("--chain", type=int, default=50)
    ap.add_argument("--blocks", type=str,
                    default="768x768,1152x1152,1152x2304,2304x1152")
    args = ap.parse_args()

    from chiaswarm_tpu.ops.flash_attention import flash_attention

    b, l, h, d = args.batch, args.seq, args.heads, args.head_dim
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, l, h, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, l, h, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, l, h, d), jnp.bfloat16)
    flops = 4.0 * b * h * l * l * d

    # the dispatch + scalar-fetch roundtrip is ~100 ms on a tunneled
    # chip — measure it with an empty "chain" and subtract it from every
    # candidate's wall clock, otherwise it biases per-call time by
    # roundtrip/chain (~2 ms at chain=50, NOT noise at ~10 ms calls)
    base_run = jax.jit(lambda qa: jnp.sum(qa.astype(jnp.float32)))
    float(base_run(q))
    base_times = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        float(base_run(q))
        base_times.append(time.perf_counter() - t0)
    roundtrip = sorted(base_times)[len(base_times) // 2]
    print(f"roundtrip baseline: {roundtrip * 1e3:.1f} ms (subtracted)")

    for spec in args.blocks.split(","):
        bq, bkv = (int(x) for x in spec.split("x"))
        try:
            # the tunneled chip's fetch roundtrip is ~100 ms — far larger
            # than one kernel run — so chain --chain dependent kernel
            # calls inside one jit (each iteration's output feeds the next
            # query; no CSE), fetch a scalar once, and subtract the
            # empty-chain roundtrip measured above
            n = args.chain

            def chained(qa, ka, va, bq=bq, bkv=bkv):
                # ka/va must be the jitted function's own parameters —
                # closing over the outer arrays would embed them as
                # program constants and blow the tunnel's request limit
                def body(_, qc):
                    return flash_attention(qc, ka, va,
                                           block_q=bq, block_kv=bkv)

                return jnp.sum(
                    jax.lax.fori_loop(0, n, body, qa).astype(jnp.float32))

            run = jax.jit(chained)
            float(run(q, k, v))
            times = []
            for _ in range(args.iters):
                t0 = time.perf_counter()
                float(run(q, k, v))
                times.append(
                    max(time.perf_counter() - t0 - roundtrip, 0.0) / n)
            ms = sorted(times)[len(times) // 2] * 1e3
            print(f"{bq}x{bkv}: {ms:8.3f} ms  "
                  f"{flops / (ms * 1e-3) / 1e12:6.1f} TFLOP/s")
        except Exception as e:  # noqa: BLE001 - report and keep sweeping
            print(f"{bq}x{bkv}: FAILED {type(e).__name__}: {str(e)[:120]}")


if __name__ == "__main__":
    main()
