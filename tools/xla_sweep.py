"""XLA compiler-option sweep driver for the headline bench.

Runs `bench.py` (headline config only) once per experiment in a fresh
subprocess with ``CHIASWARM_XLA_OPTIONS`` set, and prints a results
table. Per-executable compiler options change XLA's persistent-cache
key, so experiments never poison each other's cache entries.

Usage:
    python tools/xla_sweep.py                 # built-in experiment list
    python tools/xla_sweep.py name=k=v,k2=v2  # ad-hoc experiments

Results belong in BASELINE.md (accepted AND rejected — the reject table
is what stops the next person from re-running dead ends).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# curated conv-scheduling candidates; an unknown flag fails compilation
# and records as "invalid" (harmless — that is also an answer)
DEFAULT_EXPERIMENTS: dict[str, str] = {
    "baseline": "",
    "vmem_24m": "xla_tpu_scoped_vmem_limit_kib=24576",
    "vmem_32m": "xla_tpu_scoped_vmem_limit_kib=32768",
    "no_rwb_fusion": "xla_tpu_rwb_fusion=false",
    "async_scale2": "xla_tpu_async_copy_bandwidth_scaling_factor=2",
    "no_multi_nested": "xla_tpu_enable_multi_level_nested_loop_fusion=false",
    "flash_q4096": "",  # CHIASWARM_FLASH_BLOCK_Q sweep rides env below
}

EXTRA_ENV: dict[str, dict[str, str]] = {
    "flash_q4096": {"CHIASWARM_FLASH_BLOCK_Q": "4096",
                    "CHIASWARM_FLASH_BLOCK_KV": "1024",
                    "CHIASWARM_FLASH_VMEM_MB": "64"},
}


def run_one(name: str, options: str, iters: int = 4,
            timeout_s: int = 3600) -> dict:
    env = dict(os.environ)
    env["CHIASWARM_XLA_OPTIONS"] = options
    env["CHIASWARM_BENCH_CONFIGS"] = "headline"
    env["CHIASWARM_BENCH_ITERS"] = str(iters)
    env.update(EXTRA_ENV.get(name, {}))
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout_s)
    wall = time.perf_counter() - t0
    line = next((ln for ln in proc.stdout.splitlines()
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
        return {"name": name, "options": options, "status": "invalid",
                "wall_s": round(wall, 1), "error": " | ".join(tail)}
    data = json.loads(line)
    return {"name": name, "options": options, "status": "ok",
            "p50_s": data["p50_latency_s"],
            "images_per_sec": data["value"],
            "wall_s": round(wall, 1)}


def main() -> None:
    if len(sys.argv) > 1:
        experiments = {}
        for arg in sys.argv[1:]:
            name, _, opts = arg.partition("=")
            experiments[name] = opts
    else:
        experiments = DEFAULT_EXPERIMENTS

    results = []
    for name, opts in experiments.items():
        print(f"== {name}: {opts or '(none)'} ...", flush=True)
        result = run_one(name, opts)
        results.append(result)
        print(f"   {result}", flush=True)

    print("\nname\tstatus\tp50_s\timg/s")
    for r in results:
        print(f"{r['name']}\t{r['status']}\t{r.get('p50_s', '-')}\t"
              f"{r.get('images_per_sec', '-')}")


if __name__ == "__main__":
    main()
