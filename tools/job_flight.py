#!/usr/bin/env python3
"""Reconstruct one job's cross-worker lifecycle from its flight record.

swarmsight CLI (ISSUE 13): fetches ``GET /api/flight/<job_id>`` from a
MiniHive-protocol hive (or reads a saved record from a file) and renders
the stitched story — submit, every grant(attempt, worker), checkpoint
markers, shed/redispatch/redelivery/salvage, the exactly-once settle —
with each attempt's worker span digest aligned onto the hive clock at
its grant anchor (the residual against the settle anchor prints as
``clock_skew_s``). The heavy lifting lives in
``chiaswarm_tpu/obs/flight.py`` (stdlib-only; this tool runs without
jax); this is the thin CLI, like tools/op_roofline.py.

Formats:

- ``tree`` (default): nested events + per-attempt span trees + the
  deadline-budget attribution table.
- ``timeline``: one merged hive-clock timeline interleaving hive events
  and worker spans across workers.
- ``perfetto``: chrome-tracing JSON spanning workers (pid 0 = hive
  events, one pid per worker, one tid per attempt) — load at
  https://ui.perfetto.dev.

Examples::

    python tools/job_flight.py load-7 --hive http://127.0.0.1:8555
    python tools/job_flight.py --file flight.json --format timeline
    python tools/job_flight.py lane-0 --hive $HIVE --format perfetto \
        --out lane0.trace.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from chiaswarm_tpu.obs.flight import (  # noqa: E402
    flight_to_chrome,
    render_timeline,
    render_tree,
)


def fetch_record(hive: str, job_id: str) -> dict:
    url = f"{hive.rstrip('/')}/api/flight/{job_id}"
    try:
        with urllib.request.urlopen(url, timeout=30) as response:
            return json.loads(response.read().decode("utf-8"))
    except urllib.error.HTTPError as exc:
        if exc.code == 404:
            raise SystemExit(
                f"no flight record for job {job_id!r} at {hive} "
                f"(evicted, or the job was never submitted there)")
        raise SystemExit(f"flight fetch failed: HTTP {exc.code} ({url})")
    except urllib.error.URLError as exc:
        raise SystemExit(f"flight fetch failed: {exc.reason} ({url})")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="job_flight",
        description="render one job's cross-worker flight record")
    parser.add_argument("job_id", nargs="?",
                        help="job id to fetch (with --hive)")
    parser.add_argument("--hive",
                        help="hive base URI serving /api/flight/<id>")
    parser.add_argument("--file",
                        help="read a saved flight-record JSON instead "
                             "of fetching")
    parser.add_argument("--format", default="tree",
                        choices=("tree", "timeline", "perfetto"))
    parser.add_argument("--out",
                        help="write output here instead of stdout")
    args = parser.parse_args(argv)

    if args.file:
        with open(args.file, encoding="utf-8") as handle:
            record = json.load(handle)
    elif args.hive and args.job_id:
        record = fetch_record(args.hive, args.job_id)
    else:
        parser.error("need either --file RECORD.json, or JOB_ID --hive "
                     "URI")
        return 2  # unreachable; parser.error exits

    if args.format == "perfetto":
        body = json.dumps(flight_to_chrome(record))
    elif args.format == "timeline":
        body = render_timeline(record)
    else:
        body = render_tree(record)

    if args.out:
        with open(args.out, "w", encoding="utf-8") as handle:
            handle.write(body + "\n")
        print(f"wrote {args.format} for job "
              f"{record.get('job_id')!r} to {args.out}")
    else:
        print(body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
