"""key_audit — prove the executable-cache key tracks every trace knob.

The operator face of swarmkey's compiled side (analysis/keyflow.py): for
every trace-affecting env knob in ``compile_cache._TRACE_ENV_KNOBS``,
build the real tiny attention programs with the knob unset and set, and
assert **executable identity changes iff the key changes** — flipping a
knob must produce a different ``static_cache_key`` (so a warm slot can
never serve the stale program), and with every knob at its default the
key must be byte-identical to the historical 3-tuple (so default
deployments keep every warm slot: the taps-off stance from ISSUE 11,
generalized from one byte-identical-HLO gate into a sweep).

Each probe runs in a SUBPROCESS with a scrubbed ``CHIASWARM_*``
environment plus the scenario's overrides — the flash block/VMEM knobs
are frozen into module constants at import, so flipping them inside one
process would silently audit the stale constants (R18's import-time
face, turned on the audit itself).

Programs (all CPU-hermetic, 8 virtual devices, interpret-mode Pallas):

- ``local``     jitted ``ops.attention`` at l=64 — the einsum path by
                default; ``CHIASWARM_ATTENTION=flash`` swaps in the
                interpret-mode flash kernel (different HLO).
- ``ringmesh``  the same call traced under a seq=4 mesh
                (``parallel.context.sequence_parallel``) — local einsum
                by default (l=64 is under the ring threshold);
                ``CHIASWARM_RING_MIN_TOKENS=16`` engages the ppermute
                ring (different HLO).
- ``flash``     explicit ``impl="flash"`` — block knobs change the
                interpret-mode grid (different HLO).
- ``none``      key/fingerprint only, no build — for knobs whose HLO
                effect is TPU-only (ring-flash mode selects the fused
                kernel only on TPU; the VMEM cap and XLA compiler
                options only apply to non-interpret TPU lowering). On
                CPU these assert the KEY changes and the HLO does NOT —
                the key is deliberately a superset of what this host
                can observe.

Exit codes: 0 = every knob keyed and program-sensitive as declared ·
1 = violations (an unkeyed knob or an unexplained program change) ·
2 = probe/build error.
"""

from __future__ import annotations

import argparse
import ast
import hashlib
import json
import os
import subprocess
import sys


def _ensure_env() -> None:
    """Mirror tests/conftest.py on CPU hosts: a virtual 8-device
    platform, set BEFORE jax imports (same stance as shard_audit.py)."""
    if os.environ.get("JAX_PLATFORMS", "") in ("", "cpu"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()


# ---------------------------------------------------------------------------
# probe side (subprocess): build one program, report key + HLO identity


def _probe_args():
    import jax.numpy as jnp

    b, l, h, d = 2, 64, 2, 16
    return [jnp.linspace(0.0, 1.0, b * l * h * d,
                         dtype=jnp.float32).reshape(b, l, h, d)
            for _ in range(3)]


def _hlo_local() -> str:
    import jax

    from chiaswarm_tpu.obs.hlocost import compiled_hlo_text
    from chiaswarm_tpu.ops.attention import attention

    def f(q, k, v):
        return attention(q, k, v)

    return compiled_hlo_text(jax.jit(f).lower(*_probe_args()).compile())


def _hlo_ringmesh() -> str:
    import jax

    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
    from chiaswarm_tpu.obs.hlocost import compiled_hlo_text
    from chiaswarm_tpu.ops.attention import attention
    from chiaswarm_tpu.parallel.context import sequence_parallel

    mesh = build_mesh(MeshSpec({"seq": 4}), devices=jax.devices()[:4])

    def f(q, k, v):
        return attention(q, k, v)

    with sequence_parallel(mesh):  # dispatch resolves at TRACE time
        compiled = jax.jit(f).lower(*_probe_args()).compile()
    return compiled_hlo_text(compiled)


def _hlo_flash() -> str:
    import jax

    from chiaswarm_tpu.obs.hlocost import compiled_hlo_text
    from chiaswarm_tpu.ops.attention import attention

    def f(q, k, v):
        return attention(q, k, v, impl="flash")

    return compiled_hlo_text(jax.jit(f).lower(*_probe_args()).compile())


_PROGRAMS = {
    "local": _hlo_local,
    "ringmesh": _hlo_ringmesh,
    "flash": _hlo_flash,
}


def run_probe(program: str) -> int:
    _ensure_env()
    from chiaswarm_tpu.core.compile_cache import (
        cache_fingerprint, static_cache_key,
    )

    out = {
        "key": repr(static_cache_key(0, "audit", {"l": 64})),
        "fingerprint": repr(cache_fingerprint()),
        "hlo_sha": None,
    }
    if program != "none":
        hlo = _PROGRAMS[program]()
        out["hlo_sha"] = hashlib.sha256(hlo.encode()).hexdigest()
    print(json.dumps(out))
    return 0


# ---------------------------------------------------------------------------
# audit side (parent): scenario sweep over scrubbed subprocess probes

#: knob -> (program, override value, hlo_changes_on_cpu). A False third
#: field documents a TPU-only HLO effect: the key must still change (the
#: key is a superset of what CPU can observe), the CPU HLO must NOT.
SCENARIOS = {
    "CHIASWARM_ATTENTION": ("local", "flash", True),
    "CHIASWARM_RING_MIN_TOKENS": ("ringmesh", "16", True),
    "CHIASWARM_RING_FLASH": ("ringmesh", "scan", False),
    "CHIASWARM_FLASH_BLOCK_Q": ("flash", "16", True),
    "CHIASWARM_FLASH_BLOCK_KV": ("flash", "16", True),
    "CHIASWARM_FLASH_VMEM_MB": ("flash", "64", False),
    "CHIASWARM_XLA_OPTIONS": (
        "none", "xla_tpu_scoped_vmem_limit_kib=65536", False),
}


def _spawn_probe(program: str, overrides: dict[str, str]) -> dict:
    """One scrubbed-env probe subprocess; raises on failure."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("CHIASWARM_")}
    env.update(overrides)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--probe", program],
        env=env, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"probe {program!r} overrides={overrides} failed "
            f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> int:
    parser = argparse.ArgumentParser(
        description="audit trace-knob -> executable-cache-key "
                    "sensitivity over the real tiny attention programs")
    parser.add_argument("--probe", default=None,
                        help=argparse.SUPPRESS)  # internal subprocess mode
    parser.add_argument("--knobs", default=",".join(SCENARIOS),
                        help="comma-separated subset of: "
                             + ",".join(SCENARIOS))
    parser.add_argument("--json", default=None,
                        help="also write the full report to this path")
    args = parser.parse_args()

    if args.probe is not None:
        return run_probe(args.probe)

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    knobs = [k.strip() for k in args.knobs.split(",") if k.strip()]
    unknown = sorted(set(knobs) - set(SCENARIOS))
    if unknown:
        print(f"key_audit: unknown knob(s) {unknown}; have "
              f"{sorted(SCENARIOS)}", file=sys.stderr)
        return 2

    from chiaswarm_tpu.core.compile_cache import _TRACE_ENV_KNOBS

    report: dict = {"baseline": {}, "scenarios": {}, "violations": []}

    def violation(knob: str, message: str) -> None:
        report["violations"].append({"knob": knob, "message": message})

    uncovered = sorted(set(_TRACE_ENV_KNOBS) - set(SCENARIOS))
    if uncovered:
        violation("<coverage>",
                  f"knob(s) {uncovered} in _TRACE_ENV_KNOBS have no "
                  f"audit scenario — add one before shipping the key")

    try:
        # invariance gate: per program, two scrubbed probes must agree
        # on key AND HLO, and the default key must be the historical
        # 3-tuple (owner, tag, statics) — no knob residue
        programs = sorted({SCENARIOS[k][0] for k in knobs})
        baselines: dict[str, dict] = {}
        for prog in programs:
            first = _spawn_probe(prog, {})
            again = _spawn_probe(prog, {})
            if first["key"] != again["key"]:
                violation("<invariance>",
                          f"{prog}: default key not deterministic")
            if first["hlo_sha"] != again["hlo_sha"]:
                violation("<invariance>",
                          f"{prog}: default build not deterministic")
            key = ast.literal_eval(first["key"])
            if len(key) != 3:
                violation("<invariance>",
                          f"{prog}: default key {first['key']} is not "
                          f"the historical 3-tuple — default-off "
                          f"deployments would lose every warm slot")
            baselines[prog] = first
            report["baseline"][prog] = first

        for knob in knobs:
            prog, value, hlo_changes = SCENARIOS[knob]
            base = baselines[prog]
            probe = _spawn_probe(prog, {knob: value})
            report["scenarios"][knob] = {
                "program": prog, "value": value, "probe": probe}
            key = ast.literal_eval(probe["key"])
            base_key = ast.literal_eval(base["key"])
            if key == base_key:
                violation(knob, f"key is knob-blind: {knob}={value} "
                                f"left the key unchanged ({base['key']})"
                          )
                continue
            if key[:3] != base_key:
                violation(knob, "knob fold rewrote the historical key "
                                "prefix instead of appending — warm "
                                "slots of default deployments would be "
                                "invalidated")
            if (knob, value) not in dict(key[3:]).get("knobs", ()):
                violation(knob, f"key changed but the knob vector does "
                                f"not carry ({knob!r}, {value!r}): "
                                f"{probe['key']}")
            if knob not in probe["fingerprint"]:
                violation(knob, "persistent cache_fingerprint() does "
                                "not carry the knob")
            if prog == "none":
                continue
            if hlo_changes and probe["hlo_sha"] == base["hlo_sha"]:
                violation(knob, f"{prog}: knob changed the key but NOT "
                                f"the built executable — either the "
                                f"scenario shape misses the knob's "
                                f"effect or the knob is host-only and "
                                f"over-keys")
            if not hlo_changes and probe["hlo_sha"] != base["hlo_sha"]:
                violation(knob, f"{prog}: knob documented as TPU-only "
                                f"changed the CPU executable — promote "
                                f"the scenario to hlo_changes=True")
    except Exception as exc:  # noqa: BLE001 — a probe failure IS the report
        print(f"key_audit: {type(exc).__name__}: {exc}", file=sys.stderr)
        return 2

    report["ok"] = not report["violations"]
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report, indent=2, sort_keys=True))
    for v in report["violations"]:
        print(f"VIOLATION [{v['knob']}] {v['message']}", file=sys.stderr)
    if report["ok"]:
        print("key_audit: every knob keyed and program-sensitive as "
              "declared", file=sys.stderr)
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
