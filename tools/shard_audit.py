"""shard_audit — verify lowered programs against declared HLO contracts.

The operator face of swarmproof's compiled side (analysis/hlocheck.py):
builds the tiny-family programs the repo actually serves, captures their
post-optimization HLO through ``obs/hlocost.ProgramCapture``, and audits
the observed collective counts / matmul dtypes / donation aliasing
against a pinned contract file. CI runs it against
``tools/contracts/tiny.json`` (the test.yml "HLO contract" step); on a
TPU deployment, point ``--contract`` at a pod-specific file that pins
the real mesh's collective budget.

Programs:

- ``solo``       one tiny txt2img generate program, single device — the
                 no-collectives baseline (any collective lowered into a
                 single-chip program is a compiler surprise worth failing
                 CI over).
- ``lane``       the stepper's lane executables (encode / row-init /
                 step / decode lattice programs) for one 2-row tiny job,
                 single device — same budget.
- ``ring``       the seq-parallel ring attention shard_map on a pure
                 seq=4 mesh — MUST lower collective-permutes (the ring)
                 and MUST NOT lower an all-reduce: an all-reduce over
                 ``seq`` in this program is the runtime face of R11
                 ``replicated-psum`` (the r06 4.000x divergence).
- ``ring2axis``  the same ring bound on a data=2 x seq=4 mesh — the
                 divergence family's trigger shape (two-axis shard_map);
                 same contract as ``ring``.
- ``ring_flash`` the fused Pallas ring-flash kernel
                 (ops/ring_flash_attention.py) on the same pure seq=4
                 mesh. On this CPU audit the interpret-mode scan drives
                 the hop kernel with a ppermute rotation, so the census
                 must show the collective-permute ring and — the ISSUE-18
                 acceptance line — ZERO spurious all-reduces: an
                 all-reduce in the fused program would mean the softmax
                 combine leaked out of the carried (m, l, acc) state.
- ``ring_flash2axis``  the fused kernel on the data=2 x seq=4 trigger
                 shape; same contract.

How this relates to ``tools/divergence_bisect.py``: the bisect localizes
*where numerics first diverge at runtime*; this audit checks *what the
compiler lowered* before anything runs. When the bisect names a step, the
audit's collective census of the same program is the first thing to read.

Exit codes: 0 = contract satisfied · 1 = violations · 2 = build error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _ensure_env() -> None:
    """Mirror tests/conftest.py on CPU hosts: a virtual 8-device
    platform, set BEFORE jax imports (same stance as
    tools/divergence_bisect.py)."""
    if os.environ.get("JAX_PLATFORMS", "") in ("", "cpu"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()


DEFAULT_CONTRACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "contracts", "tiny.json")


# ---------------------------------------------------------------------------
# program builders: name -> HLO text


def build_solo() -> str:
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.obs import hlocost
    from chiaswarm_tpu.pipelines import GenerateRequest
    import chiaswarm_tpu.pipelines.diffusion as diffusion_mod

    registry = ModelRegistry(catalog=[], allow_random=True)
    req = GenerateRequest(prompt="a lighthouse", steps=2, height=64,
                          width=64, seed=7, guidance_scale=5.0)
    cap = hlocost.ProgramCapture()
    with cap.patching(diffusion_mod):
        registry.pipeline("random/tiny")(req)
    hlo = cap.largest_hlo()
    if not hlo:
        raise RuntimeError("solo capture produced no executable")
    return hlo


def build_lane() -> str:
    os.environ.setdefault("CHIASWARM_STEPPER_LANE_WIDTH", "2")
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.obs import hlocost
    from chiaswarm_tpu.serving.stepper import StepScheduler
    import chiaswarm_tpu.pipelines.diffusion as diffusion_mod

    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)
    pipe = registry.pipeline("tiny")
    cap = hlocost.ProgramCapture()
    with cap.patching(diffusion_mod):
        sched = StepScheduler()
        try:
            fut = sched.submit_request(
                pipe, prompt="audit lane", steps=2, guidance_scale=7.5,
                height=64, width=64, rows=2, seed=11)
            fut.result(timeout=600)[0].wait()
        finally:
            sched.shutdown()
    hlo = cap.largest_hlo()
    if not hlo:
        raise RuntimeError("lane capture produced no executable "
                           "(did the job ride the solo path?)")
    return hlo


def _build_ring(mesh_shape: dict) -> str:
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from chiaswarm_tpu.core.compat import shard_map
    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
    from chiaswarm_tpu.obs.hlocost import compiled_hlo_text
    from chiaswarm_tpu.parallel.ring_attention import ring_attention

    n = 1
    for v in mesh_shape.values():
        n *= v
    mesh = build_mesh(MeshSpec(dict(mesh_shape)),
                      devices=jax.devices()[:n])
    b, l, h, d = 2, 32, 2, 16
    spec = P("data" if mesh_shape.get("data", 1) > 1 else None,
             "seq", None, None)
    fn = shard_map(partial(ring_attention, axis_name="seq"),
                   mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    args = [jnp.zeros((b, l, h, d), jnp.float32) for _ in range(3)]
    compiled = jax.jit(fn).lower(*args).compile()
    return compiled_hlo_text(compiled)


def build_ring() -> str:
    return _build_ring({"seq": 4})


def build_ring2axis() -> str:
    return _build_ring({"data": 2, "seq": 4})


def _build_ring_flash(mesh_shape: dict) -> str:
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from chiaswarm_tpu.core.compat import shard_map_unchecked
    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
    from chiaswarm_tpu.obs.hlocost import compiled_hlo_text
    from chiaswarm_tpu.ops.ring_flash_attention import ring_flash_attention

    n = 1
    for v in mesh_shape.values():
        n *= v
    mesh = build_mesh(MeshSpec(dict(mesh_shape)),
                      devices=jax.devices()[:n])
    b, l, h, d = 2, 32, 2, 16
    spec = P("data" if mesh_shape.get("data", 1) > 1 else None,
             "seq", None, None)
    fn = shard_map_unchecked(
        partial(ring_flash_attention, axis_name="seq",
                mesh_axis_names=tuple(mesh.axis_names)),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    args = [jnp.zeros((b, l, h, d), jnp.float32) for _ in range(3)]
    compiled = jax.jit(fn).lower(*args).compile()
    return compiled_hlo_text(compiled)


def build_ring_flash() -> str:
    return _build_ring_flash({"seq": 4})


def build_ring_flash2axis() -> str:
    return _build_ring_flash({"data": 2, "seq": 4})


BUILDERS = {
    "solo": build_solo,
    "lane": build_lane,
    "ring": build_ring,
    "ring2axis": build_ring2axis,
    "ring_flash": build_ring_flash,
    "ring_flash2axis": build_ring_flash2axis,
}


def main() -> int:
    parser = argparse.ArgumentParser(
        description="audit lowered tiny-family programs against a "
                    "pinned HLO contract (collectives, dtypes, donation)")
    parser.add_argument("--programs",
                        default="solo,lane,ring,ring2axis,"
                                "ring_flash,ring_flash2axis",
                        help="comma-separated subset of: "
                             + ",".join(sorted(BUILDERS)))
    parser.add_argument("--contract", default=DEFAULT_CONTRACT,
                        help="contract JSON (default: "
                             "tools/contracts/tiny.json)")
    parser.add_argument("--json", default=None,
                        help="also write the full report to this path")
    parser.add_argument("--dump-hlo", default=None,
                        help="write each captured HLO under this prefix: "
                             "<prefix>.<program>.hlo.txt")
    args = parser.parse_args()

    _ensure_env()
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from chiaswarm_tpu.analysis import hlocheck

    try:
        with open(args.contract, "r", encoding="utf-8") as fh:
            contract = json.load(fh)
    except (OSError, ValueError) as exc:
        print(f"shard_audit: cannot read contract {args.contract}: {exc}",
              file=sys.stderr)
        return 2

    names = [p.strip() for p in args.programs.split(",") if p.strip()]
    unknown = sorted(set(names) - set(BUILDERS))
    if unknown:
        print(f"shard_audit: unknown program(s) {unknown}; have "
              f"{sorted(BUILDERS)}", file=sys.stderr)
        return 2

    programs: dict[str, str] = {}
    for name in names:
        try:
            programs[name] = BUILDERS[name]()
        except Exception as exc:  # noqa: BLE001 — a build failure IS the report
            print(f"shard_audit: building {name!r} failed: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
            return 2
        if args.dump_hlo:
            path = f"{args.dump_hlo}.{name}.hlo.txt"
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(programs[name])

    report = hlocheck.audit_programs(programs, contract)
    report["contract"] = args.contract
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report, indent=2, sort_keys=True))

    for v in report["violations"]:
        print(f"VIOLATION [{v['rule']}] {v['program']}: {v['message']}",
              file=sys.stderr)
    if report["ok"]:
        print("shard_audit: contract satisfied", file=sys.stderr)
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
