"""First-divergence bisect: a sharded program vs its unsharded twin.

The swarmlens payoff tool (ISSUE 11). The GSPMD divergence family
(ROADMAP item 1) presents as "the pixels differ" after a full denoise —
useless for debugging. With the numerics taps on, both runs emit
per-step per-shard summaries (obs/numerics.py), and this driver aligns
the two streams record-for-record and reports the FIRST (step, probe,
shard) where they disagree beyond tolerance — turning a five-round
mystery into a named line of code to stare at.

Configs:

- ``seq_parallel``  the known-failing latency-mode config
  (tests/test_multichip_serving.py::test_seq_parallel_serving_matches_
  single_chip): random/tiny on a data=2 x seq=4 mesh with
  CHIASWARM_RING_MIN_TOKENS=1 vs the single-chip run. Probes:
  ``diffusion.*`` (global program state) + ``ring.*`` (per-shard
  per-hop partials, sharded run only — drill-down context).
- ``seq_parallel_ring_flash``  the same paired run with the sharded
  twin's rings served by the FUSED kernel
  (CHIASWARM_ATTENTION=ring_flash, ops/ring_flash_attention.py) — the
  ISSUE-18 probe point for the item-1 hunt: when ``seq_parallel``
  diverges, rerun THIS config; a matching (step, probe) indicts the
  sharding/combine machinery both rings share, a differing one indicts
  the kernel. Drill-down probes are ``ring_flash.*`` (per-hop carried
  m/l/acc instead of the ppermute ring's per-hop partials).
- ``shard_rows``    the CHIASWARM_STEPPER_SHARD_ROWS lane twin: one
  4-row job stepped through a lane with rows sharded over the data
  axis vs the same job unsharded, compared through the ``lane_row``
  checkpoint-boundary probes (CHIASWARM_STEPPER_CKPT_EVERY=1).
- ``fixture``       a tiny intentionally-divergent scan program (the CI
  gate): twin B perturbs its carry at a known step, and the driver must
  localize exactly that (step, probe) — proving the tap -> ring ->
  align -> bisect machinery end to end without any real model.

Usage (CPU host or TPU)::

    python tools/divergence_bisect.py --config seq_parallel [--steps 4]
        [--rtol 2e-4] [--atol 1e-6] [--json out.json]

Exit codes: 0 = ran and reported; 3 = fixture mode failed to localize
the planted divergence (the CI failure signal); 1 = error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _ensure_env() -> None:
    """Mirror tests/conftest.py on CPU hosts: a virtual 8-device
    platform, set BEFORE jax imports. A real TPU pod keeps its own
    platform (the operator exports nothing)."""
    if os.environ.get("JAX_PLATFORMS", "") in ("", "cpu"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()


# ---------------------------------------------------------------------------
# stream alignment + comparison (pure functions — unit-tested without jax)
# ---------------------------------------------------------------------------

#: float summary fields compared under tolerance, in report order
FLOAT_FIELDS = ("l2", "mean", "absmax")


def record_key(rec: dict) -> tuple:
    return (rec["probe"], rec["step"], rec["shard"])


def index_stream(stream: list[dict]) -> dict[tuple, dict]:
    """(probe, step, shard) -> FIRST record (a rerun of the same
    program appends duplicate keys; the first belongs to the compared
    execution)."""
    out: dict[tuple, dict] = {}
    for rec in stream:
        out.setdefault(record_key(rec), rec)
    return out


def compare_records(a: dict, b: dict, *, rtol: float,
                    atol: float) -> str | None:
    """The field where ``a`` and ``b`` diverge beyond tolerance, or
    None. Non-finite counts compare exactly — a NaN appearing in one
    stream is a divergence regardless of magnitude tolerance."""
    if a.get("nonfinite", 0) != b.get("nonfinite", 0):
        return "nonfinite"
    for field in FLOAT_FIELDS:
        va, vb = float(a.get(field, 0.0)), float(b.get(field, 0.0))
        if abs(va - vb) > atol + rtol * max(abs(va), abs(vb)):
            return field
    return None


def _rel_err(a: float, b: float) -> float:
    denom = max(abs(a), abs(b), 1e-30)
    return abs(a - b) / denom


def _program_order_key(by_a: dict[tuple, dict]):
    """Sort key approximating PROGRAM order from stream A.

    Taps emit with ``ordered=False`` — host arrival (``seq``) is not
    guaranteed to match execution order, so a late-arriving step-3
    record must not lose "first divergence" to a step-5 record that
    beat it to the ring. Stepped records order primarily by their own
    ``step``; unstepped (step = -1) records take the step of the last
    STEPPED record that arrived before them (so a pre-loop text-encode
    probe sorts before step 0 and a post-loop output probe after the
    last step), with arrival ``seq`` breaking ties."""
    eff: dict[tuple, int] = {}
    last_stepped = -1
    for key, rec in sorted(by_a.items(), key=lambda kv: kv[1]["seq"]):
        if rec["step"] >= 0:
            last_stepped = rec["step"]
            eff[key] = rec["step"]
        else:
            eff[key] = last_stepped
    return lambda k: (eff.get(k, -1), by_a[k]["seq"])


def bisect_streams(stream_a: list[dict], stream_b: list[dict], *,
                   rtol: float = 2e-4, atol: float = 1e-6) -> dict:
    """Align two tap streams and report the first divergent key.

    Keys present in only one stream are context, not divergence (the
    unsharded twin never runs ring attention, so ``ring.*`` probes are
    expected to be B-only). Comparison order approximates stream A's
    PROGRAM order (:func:`_program_order_key`), so "first" means first
    executed, robust to unordered callback arrival."""
    by_a, by_b = index_stream(stream_a), index_stream(stream_b)
    shared = [k for k in sorted(by_a, key=_program_order_key(by_a))
              if k in by_b]
    only_a = sorted({k[0] for k in by_a if k not in by_b})
    only_b = sorted({k[0] for k in by_b if k not in by_a})
    divergent: list[dict] = []
    bit_only = 0
    for key in shared:
        a, b = by_a[key], by_b[key]
        field = compare_records(a, b, rtol=rtol, atol=atol)
        if field is not None:
            divergent.append({
                "probe": key[0], "step": key[1], "shard": key[2],
                "field": field,
                "a": {f: a.get(f) for f in FLOAT_FIELDS + ("nonfinite",)},
                "b": {f: b.get(f) for f in FLOAT_FIELDS + ("nonfinite",)},
                "rel_err": round(_rel_err(a.get(field, 0.0),
                                          b.get(field, 0.0)), 8)
                if field != "nonfinite" else None,
            })
        elif a.get("checksum") != b.get("checksum"):
            # floats agree under tolerance but content bits differ —
            # normal for reordered partitioned reductions; counted so
            # a bit-exactness audit can see it
            bit_only += 1
    report = {
        "compared": len(shared),
        "divergent": len(divergent),
        "bit_only_differences": bit_only,
        "tolerances": {"rtol": rtol, "atol": atol},
        "probes_only_in_a": only_a,
        "probes_only_in_b": only_b,
        "first_divergence": divergent[0] if divergent else None,
        "divergences": divergent[:20],
    }
    return report


# ---------------------------------------------------------------------------
# paired runs
# ---------------------------------------------------------------------------


def _drain_ring():
    from chiaswarm_tpu.obs import numerics

    numerics.flush()
    # shared-structure probes (attn.*) count call sites from zero per
    # run, so twin call indices align
    numerics.TAPS.reset_trace_seq()
    return numerics.RING.drain()


def run_seq_parallel(steps: int) -> tuple[list[dict], list[dict], dict]:
    """The failing latency-mode config: single-chip vs data=2 x seq=4."""
    os.environ.setdefault("CHIASWARM_NUMERICS", "diffusion,ring")
    os.environ["CHIASWARM_RING_MIN_TOKENS"] = "1"

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.obs import numerics
    from chiaswarm_tpu.pipelines import GenerateRequest

    registry = ModelRegistry(catalog=[], allow_random=True)
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 2, "seq": 4}))
    req = GenerateRequest(prompt="a lighthouse", steps=steps, height=64,
                          width=64, seed=21, guidance_scale=5.0)
    numerics.RING.clear()
    registry.pipeline("random/tiny")(req)
    stream_a = _drain_ring()
    registry.pipeline("random/tiny", mesh=pool.slots[0].mesh)(req)
    stream_b = _drain_ring()
    context = {"mesh": {"data": 2, "seq": 4}, "family": "tiny",
               "steps": steps, "size": 64, "seed": 21,
               "ring_min_tokens": 1}
    return stream_a, stream_b, context


def run_seq_parallel_ring_flash(
        steps: int) -> tuple[list[dict], list[dict], dict]:
    """The ``seq_parallel`` pair with the fused ring-flash kernel as
    the sharded twin's ring (the env knob is advisory, so the
    single-chip twin simply keeps its local paths). The ``ring`` tap
    family string-prefix-matches ``ring_flash.*`` too, so the per-hop
    carried state records without extra env surface."""
    os.environ["CHIASWARM_ATTENTION"] = "ring_flash"
    try:
        stream_a, stream_b, context = run_seq_parallel(steps)
    finally:
        os.environ.pop("CHIASWARM_ATTENTION", None)
    context["attention"] = "ring_flash"
    return stream_a, stream_b, context


def run_shard_rows(steps: int) -> tuple[list[dict], list[dict], dict]:
    """The lane twin: one 4-row job through an unsharded lane vs the
    same job with rows sharded over the data axis
    (CHIASWARM_STEPPER_SHARD_ROWS=1), compared via the per-row
    checkpoint-boundary probes at every step."""
    os.environ.setdefault("CHIASWARM_NUMERICS", "lane_row")
    os.environ["CHIASWARM_STEPPER_CKPT_EVERY"] = "1"
    os.environ["CHIASWARM_STEPPER_LANE_WIDTH"] = "4"

    import jax

    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.obs import numerics
    from chiaswarm_tpu.serving.stepper import StepScheduler

    # the FAILING mesh is dp x tp (the bench slot shape): on a pure
    # data mesh the sharded lane is bit-identical to its twin — the
    # divergence needs the second (model) axis, exactly like the
    # seq-parallel family needs data x seq (r06 bisect finding)
    if len(jax.devices()) >= 8:
        mesh_spec = {"data": 4, "model": 2}
        mesh = build_mesh(MeshSpec(dict(mesh_spec)))
    else:
        n_dev = min(4, len(jax.devices()))
        mesh_spec = {"data": n_dev}
        mesh = build_mesh(MeshSpec(dict(mesh_spec)),
                          devices=jax.devices()[:n_dev])
    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)
    pipe = registry.pipeline("tiny", mesh=mesh)

    def one_run(shard_rows: bool) -> list[dict]:
        os.environ["CHIASWARM_STEPPER_SHARD_ROWS"] = \
            "1" if shard_rows else "0"
        sched = StepScheduler()
        numerics.RING.clear()
        fut = sched.submit_request(
            pipe, prompt="bisect twin", steps=steps, guidance_scale=7.5,
            height=64, width=64, rows=4, seed=77)
        fut.result(timeout=600)[0].wait()
        sched.shutdown()
        return _drain_ring()

    stream_a = one_run(False)
    stream_b = one_run(True)
    context = {"mesh": mesh_spec, "family": "tiny", "steps": steps,
               "rows": 4, "size": 64, "seed": 77, "ckpt_every": 1}
    return stream_a, stream_b, context


FIXTURE_DIVERGE_STEP = 3


def run_fixture(steps: int = 6) -> tuple[list[dict], list[dict], dict]:
    """Tiny intentionally-divergent scan pair: twin B's carry is
    perturbed at step FIXTURE_DIVERGE_STEP. The CI gate asserts the
    bisect localizes exactly that step on the ``fixture.carry`` probe."""
    os.environ.setdefault("CHIASWARM_NUMERICS", "fixture")

    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.obs import numerics

    def make_run(perturb: float):
        def fn(x):
            def body(carry, i):
                carry = carry * 1.01 + 0.001
                carry = jnp.where(i == FIXTURE_DIVERGE_STEP,
                                  carry + perturb, carry)
                carry = numerics.tap("fixture.carry", carry, step=i)
                return carry, None
            out, _ = jax.lax.scan(body, x, jnp.arange(steps))
            return numerics.tap("fixture.out", out)

        numerics.RING.clear()
        jax.block_until_ready(jax.jit(fn)(jnp.ones((8, 8))))
        return _drain_ring()

    stream_a = make_run(0.0)
    stream_b = make_run(1e-2)
    context = {"steps": steps, "planted_step": FIXTURE_DIVERGE_STEP}
    return stream_a, stream_b, context


CONFIGS = {
    "seq_parallel": run_seq_parallel,
    "seq_parallel_ring_flash": run_seq_parallel_ring_flash,
    "shard_rows": run_shard_rows,
    "fixture": run_fixture,
}


def main() -> int:
    parser = argparse.ArgumentParser(
        description="bisect a sharded program against its unsharded "
                    "twin via swarmlens numerics taps")
    parser.add_argument("--config", choices=sorted(CONFIGS),
                        default="fixture")
    parser.add_argument("--steps", type=int, default=None,
                        help="denoise/scan steps (default: 4 for model "
                             "configs, 6 for the fixture)")
    parser.add_argument("--rtol", type=float, default=2e-4)
    parser.add_argument("--atol", type=float, default=1e-6)
    parser.add_argument("--json", default=None,
                        help="also write the full report to this path")
    parser.add_argument("--dump-streams", default=None,
                        help="write both raw streams (JSONL) under this "
                             "prefix: <prefix>.a.jsonl / <prefix>.b.jsonl")
    args = parser.parse_args()

    _ensure_env()
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    steps = args.steps or (6 if args.config == "fixture" else 4)
    stream_a, stream_b, context = CONFIGS[args.config](steps)

    report = bisect_streams(stream_a, stream_b, rtol=args.rtol,
                            atol=args.atol)
    report["config"] = args.config
    report["context"] = context
    report["stream_sizes"] = {"a": len(stream_a), "b": len(stream_b)}

    if args.dump_streams:
        from chiaswarm_tpu.obs import numerics

        numerics.dump(args.dump_streams + ".a.jsonl", stream_a)
        numerics.dump(args.dump_streams + ".b.jsonl", stream_b)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)

    first = report["first_divergence"]
    print(json.dumps(report, indent=2, sort_keys=True))
    if first:
        print(f"\nFIRST DIVERGENCE: step {first['step']}, probe "
              f"{first['probe']}, shard {first['shard']} "
              f"({first['field']}: {first['a'][first['field']]} vs "
              f"{first['b'][first['field']]})", file=sys.stderr)
    else:
        print("\nno divergence beyond tolerance", file=sys.stderr)

    if args.config == "fixture":
        ok = (first is not None
              and first["probe"] == "fixture.carry"
              and first["step"] == FIXTURE_DIVERGE_STEP)
        if not ok:
            print("fixture gate FAILED: planted divergence at step "
                  f"{FIXTURE_DIVERGE_STEP} was not localized",
                  file=sys.stderr)
            return 3
        print("fixture gate ok: planted divergence localized",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
